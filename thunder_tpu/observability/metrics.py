"""Low-overhead process-wide metrics registry.

The runtime half of the observability subsystem (reference analogue:
thunder's ``CompileStats`` timers, generalized): counters, gauges, and
histograms that the dispatch/compile paths update and
``thunder_tpu.monitor.report()`` exports — as a nested dict, a JSON dump, or
Prometheus text exposition format.

Design constraints (the reason this is not a prometheus_client dependency):

- **Disabled must be free.** Every mutate method checks one module-level
  flag and returns; the GPT-block dispatch bench budget is <1% overhead with
  observability off and <5% with metrics on (BENCHMARKS.md).
- **No locks on the hot path.** CPython dict ops are atomic enough for
  monotonic counters; a torn read in ``report()`` costs one sample, never a
  crash. (Compile-side metrics are effectively single-threaded anyway.)
- **Process-wide, not per-function.** Per-function counters live on
  ``CompileStats`` (``thunder_tpu.cache_info``); this registry aggregates
  across every compiled function so one scrape describes the whole server.

Enable with ``THUNDER_TPU_METRICS=1`` or :func:`enable` (the
``thunder_tpu.monitor`` facade re-exports both spellings).
"""

from __future__ import annotations

import json
import os
import time
from bisect import bisect_left
from typing import Any, Optional


_state = {
    "enabled": os.environ.get("THUNDER_TPU_METRICS", "").strip().lower()
    not in ("", "0", "false", "off")
}


def enable() -> None:
    _state["enabled"] = True


def disable() -> None:
    _state["enabled"] = False


def enabled() -> bool:
    return _state["enabled"]


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items())) if labels else ()


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


def _escape_label_value(v: Any) -> str:
    """Prometheus text-exposition escaping for label values: backslash,
    double quote, and newline must be escaped or the scrape line is
    malformed (host/process labels carry hostnames — arbitrary strings)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str_prom(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in key) + "}"


class _Metric:
    kind = "untyped"
    __slots__ = ("name", "help", "_values")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._values: dict[tuple, Any] = {}

    def clear(self) -> None:
        self._values.clear()

    def series(self) -> dict[tuple, Any]:
        return dict(self._values)


class Counter(_Metric):
    """Monotonically increasing count (optionally labelled).

    ``always=True`` marks an *always-export* counter: its (unlabelled)
    series appears in ``prometheus_text`` as an explicit 0 even before the
    first increment and even with the metrics gate off — reserved for
    counters whose absence would hide a loss of observability itself (the
    event-log drop counter): a ``/healthz`` or scrape-side alert on
    ``> 0`` only works if the 0 is on the wire to begin with (ISSUE 15
    satellite)."""

    kind = "counter"
    __slots__ = ("always",)

    def __init__(self, name: str, help: str = "", always: bool = False):
        super().__init__(name, help)
        self.always = bool(always)

    def inc(self, n: float = 1, **labels) -> None:
        if not _state["enabled"]:
            return
        k = tuple(sorted(labels.items())) if labels else ()
        self._values[k] = self._values.get(k, 0) + n

    def inc_always(self, n: float = 1, **labels) -> None:
        """Increment even with metrics disabled — reserved for counters
        whose silence would hide a loss of observability itself (e.g. the
        event-log drop counter): they must appear in ``monitor.report()``
        unconditionally."""
        k = tuple(sorted(labels.items())) if labels else ()
        self._values[k] = self._values.get(k, 0) + n

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Last-written value (optionally labelled); ``set_max`` keeps the peak."""

    kind = "gauge"
    __slots__ = ()

    def set(self, v: float, **labels) -> None:
        if not _state["enabled"]:
            return
        self._values[_label_key(labels)] = v

    def set_max(self, v: float, **labels) -> None:
        if not _state["enabled"]:
            return
        k = _label_key(labels)
        cur = self._values.get(k)
        if cur is None or v > cur:
            self._values[k] = v

    def value(self, **labels) -> Optional[float]:
        return self._values.get(_label_key(labels))


# Log-spaced default buckets: cover 1us..100s when observing microseconds.
_DEFAULT_BUCKETS = (1.0, 10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8)


class Histogram(_Metric):
    """count/sum/min/max plus log-spaced bucket counts.

    Hot-path discipline: ``observe`` stores RAW per-bucket counts via one
    bisect (the last slot is the +Inf overflow); the Prometheus-style
    cumulative counts are derived at render time (``summary``/
    ``prometheus_text``), keeping the per-observation cost flat."""

    kind = "histogram"
    __slots__ = ("buckets",)

    def __init__(self, name: str, help: str = "", buckets: tuple = _DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(buckets)

    def observe(self, v: float, **labels) -> None:
        if not _state["enabled"]:
            return
        k = tuple(sorted(labels.items())) if labels else ()
        s = self._values.get(k)
        if s is None:
            s = self._values[k] = {
                "count": 0, "sum": 0.0, "min": v, "max": v,
                "raw_buckets": [0] * (len(self.buckets) + 1),
            }
            s["count"] = 1
            s["sum"] = v
            s["raw_buckets"][bisect_left(self.buckets, v)] = 1
            return
        s["count"] += 1
        s["sum"] += v
        if v < s["min"]:
            s["min"] = v
        elif v > s["max"]:
            s["max"] = v
        s["raw_buckets"][bisect_left(self.buckets, v)] += 1

    def _cumulative(self, raw: list) -> list:
        out = []
        acc = 0
        for c in raw[:-1]:  # last slot is the +Inf overflow
            acc += c
            out.append(acc)
        return out

    def summary(self, **labels) -> Optional[dict]:
        s = self._values.get(_label_key(labels))
        if s is None:
            return None
        out = {k: s[k] for k in ("count", "sum", "min", "max")}
        out["bucket_counts"] = self._cumulative(s["raw_buckets"])
        out["mean"] = s["sum"] / s["count"] if s["count"] else 0.0
        return out


class MetricsRegistry:
    """Name → metric, get-or-create. One process-wide instance (``REGISTRY``)
    plus constructible for tests."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, help, **kw)
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, not {cls.kind}"
            )
        return m

    def counter(self, name: str, help: str = "", always: bool = False) -> Counter:
        return self._get_or_create(Counter, name, help, always=always)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "", buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def reset(self) -> None:
        """Clear every metric's values (definitions stay registered)."""
        for m in self._metrics.values():
            m.clear()

    # -- export ---------------------------------------------------------------

    def report(self) -> dict:
        """Nested snapshot: name -> {kind, help, values: {label_str: value}}.
        Histogram values are the count/sum/min/max/mean summaries."""
        out: dict[str, Any] = {}
        for name, m in sorted(self._metrics.items()):
            values: dict[str, Any] = {}
            for k in list(m._values):
                if isinstance(m, Histogram):
                    values[_label_str(k)] = m.summary(**dict(k))
                else:
                    values[_label_str(k)] = m._values.get(k)
            out[name] = {"kind": m.kind, "help": m.help, "values": values}
        return out

    def report_compact(self) -> dict:
        """Flat {name+labels: value} snapshot with empty series dropped —
        what ``bench.py`` embeds in its JSON line."""
        out: dict[str, Any] = {}
        for name, m in sorted(self._metrics.items()):
            for k in list(m._values):
                if isinstance(m, Histogram):
                    s = m.summary(**dict(k))
                    if s:
                        out[f"{name}{_label_str(k)}"] = {
                            kk: s[kk] for kk in ("count", "sum", "mean", "min", "max")
                        }
                else:
                    out[f"{name}{_label_str(k)}"] = m._values.get(k)
        return out

    def prometheus_text(self, extra_labels: Optional[dict] = None) -> str:
        """Prometheus text exposition format (histograms as _bucket/_sum/_count).

        ``extra_labels`` are merged into every series — the host/process
        dimension for multi-host scrapes (``monitor.prometheus_text(
        include_host=True)`` passes ``{"host": ..., "pid": ...}``), so one
        aggregator can tell the writers of a fleet apart. Label values are
        escaped per the exposition format."""
        extra = dict(extra_labels) if extra_labels else {}
        lines: list[str] = []
        for name, m in sorted(self._metrics.items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if getattr(m, "always", False) and not m._values:
                # Always-export counters put their 0 on the wire so the
                # scrape side can alert on >0 (and /healthz can read the
                # series) even before anything went wrong — and regardless
                # of the metrics gate, matching inc_always (ISSUE 6/15).
                lines.append(f"{name}{_label_str_prom(_label_key(extra))} 0")
            for k in list(m._values):
                base = dict(extra, **dict(k))
                lk = _label_str_prom(_label_key(base))
                if isinstance(m, Histogram):
                    s = m._values.get(k)
                    if s is None:
                        continue
                    for le, c in zip(m.buckets, m._cumulative(s["raw_buckets"])):
                        blk = _label_str_prom(_label_key(dict(base, le=repr(le))))
                        lines.append(f"{name}_bucket{blk} {c}")
                    blk = _label_str_prom(_label_key(dict(base, le="+Inf")))
                    lines.append(f"{name}_bucket{blk} {s['count']}")
                    lines.append(f"{name}_sum{lk} {s['sum']}")
                    lines.append(f"{name}_count{lk} {s['count']}")
                else:
                    lines.append(f"{name}{lk} {m._values.get(k)}")
        return "\n".join(lines) + "\n"

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"ts": time.time(), "metrics": self.report()}, f, indent=2, default=str)
            f.write("\n")


REGISTRY = MetricsRegistry()

# -- the framework's own metrics ----------------------------------------------
# Registered eagerly so report()/prometheus_text() list them (with empty
# series) even before traffic, and so hot paths share these handles instead
# of doing name lookups.

DISPATCH_US = REGISTRY.histogram(
    "thunder_tpu_dispatch_us",
    "Host-side dispatch wall time per compiled-function call (us), cache lookup through result",
)
CACHE_LOOKUP_US = REGISTRY.histogram(
    "thunder_tpu_cache_lookup_us", "Cache lookup (guard evaluation) time per call (us)"
)
CACHE_HITS = REGISTRY.counter(
    "thunder_tpu_cache_hits_total",
    "Cache hits across all compiled functions, labelled kind=fast|slow|same_input|module",
)
CACHE_MISSES = REGISTRY.counter(
    "thunder_tpu_cache_misses_total", "Cache misses (each triggers a compile)"
)
COMPILES = REGISTRY.counter(
    "thunder_tpu_compiles_total", "Trace compilations (acquisition through staging)"
)
RECOMPILES = REGISTRY.counter(
    "thunder_tpu_recompiles_total", "Compilations beyond a function's first — the storm signal"
)
COMPILE_MS = REGISTRY.histogram(
    "thunder_tpu_compile_ms", "End-to-end compile time per entry (ms)"
)
# The metric that doubled r4→r5 without anyone noticing: the TOTAL seconds a
# compile class spends in XLA (staging + backend compile), not just the
# trace-side per-pass ms. Labelled cls=exact|bucketed (dispatch first runs) or
# cls=bench_forward|bench_train_step (bench.py's measured compiles).
XLA_COMPILE_S = REGISTRY.histogram(
    "thunder_tpu_xla_compile_s",
    "End-to-end XLA compile+first-run seconds, labelled by compile class",
    buckets=(0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0),
)
PASS_MS = REGISTRY.histogram(
    "thunder_tpu_pass_ms", "Per-transform-pass duration (ms), labelled by pass"
)
CLAIMED_BSYMS = REGISTRY.counter(
    "thunder_tpu_claimed_bsyms_total", "Executor-claim breakdown of execution traces, labelled by executor"
)
COLLECTIVE_BYTES = REGISTRY.counter(
    "thunder_tpu_collective_bytes_traced_total",
    "Bytes moved by collectives per traced program (static, from trace metadata)",
)
PADDING_WASTE_ELEMENTS = REGISTRY.counter(
    "thunder_tpu_padding_waste_elements_total",
    "Elements of bucket padding dispatched (padded minus true extents)",
)
BUCKET_COMPILES = REGISTRY.counter(
    "thunder_tpu_bucket_compiles_total", "Symbolic-values compiles, one per shape bucket"
)
SHARP_EDGES = REGISTRY.counter(
    "thunder_tpu_sharp_edges_total", "Sharp-edge observations during tracing"
)
NAN_WATCH_TRIPS = REGISTRY.counter(
    "thunder_tpu_nan_watch_trips_total", "NaN/Inf watch detections, labelled by symbol"
)
INSTRUMENTED_OP_US = REGISTRY.histogram(
    "thunder_tpu_instrumented_op_us", "Per-op wall time under the OpTimer hook (us), labelled by symbol"
)
DEVICE_MEM_HIGH_WATER = REGISTRY.gauge(
    "thunder_tpu_device_mem_high_water_bytes",
    "Peak device memory observed by the MemoryHighWater hook",
)

# -- distributed observatory (docs/observability.md "distributed telemetry") --

# The opaque total XLA_COMPILE_S records, decomposed: trace/claim/staging/
# backend-compile/persistent-cache spans per compile, labelled by phase —
# the histogram the compile_phase events aggregate into.
COMPILE_PHASE_S = REGISTRY.histogram(
    "thunder_tpu_compile_phase_s",
    "Compile pipeline phase duration in seconds, labelled phase=trace|transforms|"
    "claim|staging|xla_compile (cache=hit|miss when the persistent cache resolved it)",
    buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0),
)
# Cross-host health (analysis/events.host_health over merged per-host logs):
# per-host mean step seconds, and the fleet spread ratio whose growth is the
# straggler signal (1.0 = perfectly even).
HOST_STEP_TIME_S = REGISTRY.gauge(
    "thunder_tpu_host_step_time_s",
    "Mean training-step seconds per host from merged step_time events, labelled by host",
)
HOST_STEP_SPREAD = REGISTRY.gauge(
    "thunder_tpu_host_step_time_spread_ratio",
    "Slowest host mean step time over fleet median (straggler suspect when above threshold)",
)

# -- resilience (thunder_tpu/resilience; docs/robustness.md) -------------------

FAULTS_INJECTED = REGISTRY.counter(
    "thunder_tpu_faults_injected_total",
    "Chaos-harness fault injections, labelled by seam",
)
EXECUTOR_DEMOTIONS = REGISTRY.counter(
    "thunder_tpu_executor_demotions_total",
    "Quarantined (sym, executor) pairs after kernel failures, labelled by executor",
)
COMPILE_DEOPTS = REGISTRY.counter(
    "thunder_tpu_compile_deopts_total",
    "Compile de-optimization ladder escalations, labelled by level",
)
NAN_GUARD_TRIPS = REGISTRY.counter(
    "thunder_tpu_nan_guard_trips_total",
    "Post-step isfinite guard trips (jit(on_nan=...))",
)
CHECKPOINT_RETRIES = REGISTRY.counter(
    "thunder_tpu_checkpoint_retries_total",
    "Checkpoint save attempts retried after transient I/O errors",
)
# Mesh-wide fault tolerance (ISSUE 9; docs/robustness.md "distributed
# resilience"): the collective watchdog, elastic resume, and SDC guard.
WATCHDOG_TIMEOUTS = REGISTRY.counter(
    "thunder_tpu_collective_watchdog_timeouts_total",
    "Guarded dispatches abandoned after the collective timeout, labelled by fn",
)
ELASTIC_RESUMES = REGISTRY.counter(
    "thunder_tpu_elastic_resumes_total",
    "Checkpoint restores resharded onto a different mesh shape",
)
SDC_SUSPECTS = REGISTRY.counter(
    "thunder_tpu_sdc_suspects_total",
    "Replica-checksum divergences (or loss spikes) flagged by the SDC guard",
)
SDC_RERUNS = REGISTRY.counter(
    "thunder_tpu_sdc_reruns_total",
    "Quarantined-step re-runs by the SDC guard, labelled ok=true|false",
)
# Fleet autopilot (ISSUE 11; docs/robustness.md "fleet autopilot"): the
# policy engine's choices, and the soak driver's headline goodput.
AUTOPILOT_DECISIONS = REGISTRY.counter(
    "thunder_tpu_autopilot_decisions_total",
    "Fleet-autopilot policy decisions, labelled by actuator "
    "(elastic_resume|quarantine_rerun|deopt_escalate|checkpoint_halt)",
)
SOAK_GOODPUT = REGISTRY.gauge(
    "thunder_tpu_soak_goodput_tokens_per_sec",
    "Soak-run goodput: useful tokens/sec over wall clock, discounted by the "
    "measured resilience overhead (scripts/soak_fleet.py)",
)
WATCHDOG_UNGUARDED = REGISTRY.counter(
    "thunder_tpu_collective_watchdog_unguarded_total",
    "Guarded dispatches run UNguarded because the abandoned-worker cap "
    "(THUNDER_TPU_WATCHDOG_MAX_ABANDONED) was reached",
)
# Tiered checkpointing (ISSUE 14; docs/robustness.md "tiered
# checkpointing"): the step-boundary snapshot stall (the only hot-path
# cost), the background writer's disk commits, and the restore-tier ladder.
SNAPSHOTS = REGISTRY.counter(
    "thunder_tpu_snapshots_total",
    "Step-boundary RAM snapshots taken (CheckpointManager.snapshot)",
)
CHECKPOINT_STALL_MS = REGISTRY.histogram(
    "thunder_tpu_checkpoint_stall_ms",
    "Milliseconds the training loop stalls per snapshot (device->host copy "
    "+ crc32; disk durability runs on the background writer)",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0),
)
SNAPSHOT_FLUSHES = REGISTRY.counter(
    "thunder_tpu_snapshot_flushes_total",
    "Background/synchronous disk flushes of RAM snapshots, labelled "
    "ok=true|false",
)
RESTORES = REGISTRY.counter(
    "thunder_tpu_restores_total",
    "Tiered checkpoint restores, labelled by winning tier "
    "(local|peer|disk)",
)
# inc_always + always-export: a dropped observability sink must be visible
# even with the metrics gate off — silent loss of the event log is the
# failure mode this counter exists to expose (monitor.report() lists it
# unconditionally, prometheus_text puts its 0 on the wire so scrapers and
# /healthz can degrade on the first drop — ISSUE 15 satellite).
EVENT_LOG_DROPPED = REGISTRY.counter(
    "thunder_tpu_event_log_dropped_total",
    "Event-log sinks disabled after I/O failure (each loses all later events)",
    always=True,
)

# -- live ops plane (ISSUE 15; docs/observability.md "ops plane") --------------

OPS_REQUESTS = REGISTRY.counter(
    "thunder_tpu_ops_requests_total",
    "Ops-server HTTP requests, labelled by route "
    "(/metrics|/healthz|/debug/state|/debug/flightrec)",
)
ANOMALIES = REGISTRY.counter(
    "thunder_tpu_anomalies_total",
    "Streaming-detector anomalies, labelled by kind "
    "(step_time_drift|goodput_drop|recompile_storm|host_spread)",
)
# inc_always + always-export like the drop counter: a flight-recorder dump
# means a fault fired — monitor.report() must show it with metrics off.
FLIGHTREC_DUMPS = REGISTRY.counter(
    "thunder_tpu_flightrec_dumps_total",
    "Flight-recorder black-box dumps, labelled by trigger reason",
    always=True,
)

# -- continuous roofline ledger (ISSUE 19) -------------------------------------

# Always-export: ok="false" means the profiler plugin was missing and the
# bracket silently degraded to wall clock — every duty-cycled roofline
# probe on that backend measures nothing. /healthz degrades its `profile`
# component off this counter, so it must be visible with metrics off.
PROFILE_CAPTURES = REGISTRY.counter(
    "thunder_tpu_profile_captures_total",
    "Profiler bracket attempts, labelled ok=true|false (false = plugin "
    "missing, wall-clock-only capture; see the profile_degraded event)",
    always=True,
)
# Always-export so "zero probes with sampling off" is checkable from the
# wire, not just from sampler state (lint_traces --roofline asserts both).
ROOFLINE_PROBES = REGISTRY.counter(
    "thunder_tpu_roofline_probes_total",
    "Duty-cycled roofline probes (one profiled step folded into the "
    "per-op ledger)",
    always=True,
)

# -- fleet critical-path ledger (ISSUE 20) -------------------------------------

# Always-export: "zero timeline steps with a fleet run in flight" means the
# recorder is dead or unarmed — /healthz's `timeline` component and the CI
# smoke both key on this counter being on the wire with metrics off.
CRITPATH_STEPS = REGISTRY.counter(
    "thunder_tpu_critpath_steps_total",
    "Fleet steps folded into the critical-path ledger "
    "(observability/timeline.py)",
    always=True,
)
CRITPATH_FRACTION = REGISTRY.gauge(
    "thunder_tpu_critpath_fraction",
    "EWMA share of fleet step wall time on the critical path, labelled by "
    "class (compute|exposed_ici|exposed_dcn|straggler_wait|stall|idle)",
)
CRITPATH_SKEW_MS = REGISTRY.gauge(
    "thunder_tpu_critpath_clock_skew_ms",
    "Estimated per-host clock skew vs the fleet-median clock, from "
    "collective rendezvous alignment, labelled by host",
)
