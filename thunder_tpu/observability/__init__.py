"""Runtime observability: metrics registry, event log, instrumentation,
profiler bracketing.

Four coordinated parts (ISSUE 4; reference analogues: thunder's
CompileStats/last_traces/TraceProvenance + profile.py NVTX markers):

- :mod:`~thunder_tpu.observability.metrics` — process-wide counters/gauges/
  histograms (dispatch latency, cache hit/miss/recompile, padding waste,
  executor-claim breakdown, collective bytes). Exported via
  ``thunder_tpu.monitor.report()`` / JSON / Prometheus text. Enable with
  ``THUNDER_TPU_METRICS=1`` or ``thunder_tpu.monitor.enable()``.
- :mod:`~thunder_tpu.observability.events` — structured JSONL event log
  (compile start/end with per-pass durations, cache, bucket, sharp-edge
  events), gated by ``THUNDER_TPU_EVENTS=<path>`` or ``jit(events=...)``;
  replayed by ``scripts/lint_traces.py --events``.
- :mod:`~thunder_tpu.observability.instrument` — the per-op instrumentation
  transform: ``jit(fn, debug_watch="nan")`` (NaN/Inf watch with BoundSymbol
  + provenance attribution), ``instrument="time"``/``"memory"``/custom hooks.
- :mod:`~thunder_tpu.observability.profile` — ``thunder_tpu.profile(fn,
  *args)``: jax.profiler-bracketed steps → an xprof-ready trace dir;
  annotated codegen stamps trace-line + pass provenance into HLO metadata.
- :mod:`~thunder_tpu.observability.attribution` — parses the profiler's
  trace-events and aggregates measured device time back onto trace lines
  (``L<idx>.<sym>#<pass>`` scopes), joinable with the static cost model
  (``thunder_tpu/analysis/cost.py``) into the roofline/MFU report exposed
  as ``thunder_tpu.monitor.attribution_report()``.
- :mod:`~thunder_tpu.observability.roofline` — the continuous spelling of
  the above (ISSUE 19): a duty-cycled in-loop sampler folding probe joins
  into a bounded per-op ledger (``/debug/roofline``,
  ``monitor.roofline_report()``), with per-op measured/predicted drift
  streamed into the detector bank as ``cost_model_drift`` /
  ``kernel_regression`` anomalies.

Import structure: ``metrics`` and ``events`` are stdlib-only (safe to import
from ``core/trace.py`` and ``common.py`` without cycles); ``instrument`` and
``profile`` import core modules and are loaded lazily here.
"""

from __future__ import annotations

from thunder_tpu.observability import events, metrics  # noqa: F401
from thunder_tpu.observability.events import EventLog, emit_event  # noqa: F401
from thunder_tpu.observability.metrics import REGISTRY, MetricsRegistry  # noqa: F401

_LAZY = {
    "FlightRecorder": "thunder_tpu.observability.opsplane",
    "OpsServer": "thunder_tpu.observability.opsplane",
    "DetectorBank": "thunder_tpu.observability.detect",
    "DetectorConfig": "thunder_tpu.observability.detect",
    "HostHealthAccumulator": "thunder_tpu.observability.detect",
    "NaNWatcher": "thunder_tpu.observability.instrument",
    "NaNWatchError": "thunder_tpu.observability.instrument",
    "OpTimer": "thunder_tpu.observability.instrument",
    "MemoryHighWater": "thunder_tpu.observability.instrument",
    "InstrumentationHook": "thunder_tpu.observability.instrument",
    "instrument_reports": "thunder_tpu.observability.instrument",
    "profile": "thunder_tpu.observability.profile",
    "Attribution": "thunder_tpu.observability.attribution",
    "ScopeRef": "thunder_tpu.observability.attribution",
    "attribute": "thunder_tpu.observability.attribution",
    "parse_scope": "thunder_tpu.observability.attribution",
    "hlo_scope_map": "thunder_tpu.observability.attribution",
    "join_cost_attribution": "thunder_tpu.observability.attribution",
    "RooflineSampler": "thunder_tpu.observability.roofline",
    "RooflineLedger": "thunder_tpu.observability.roofline",
    "RooflineEntry": "thunder_tpu.observability.roofline",
    "BandDetector": "thunder_tpu.observability.detect",
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    val = getattr(importlib.import_module(target), name)
    globals()[name] = val
    return val
