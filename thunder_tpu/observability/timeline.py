"""Fleet critical-path ledger: skew-aligned cross-host step timelines.

The roofline ledger (observability/roofline.py) explains *per-op* time and
the ops plane (observability/opsplane.py) explains *per-host* health; this
module answers the fleet-level question between them (ISSUE 20): **where
does one training step's wall time go across the whole fleet** — compute vs
exposed ICI vs exposed DCN vs straggler-wait vs host stalls vs idle.

Three layers:

1. **Clock alignment** (:func:`estimate_skew`). Per-host event logs carry
   per-host wall clocks; merging them on raw ``ts`` makes cross-host
   causality fiction. Collective completions are rendezvous barriers — every
   participant leaves at (physically) the same instant — so matched
   ``collective``/``hier_all_reduce`` records with a shared ``(fn, cid)``
   key yield one offset sample per host per barrier: ``host ts − fleet
   median ts``. A robust estimator (median offset, MAD spread, least-squares
   drift) turns the samples into per-host :class:`SkewEstimate` with a
   confidence in ``(0, 1]``; a host whose residuals are wide (an unstable
   clock, not merely a shifted one) is flagged ``outlier``. Offsets are
   relative to the fleet-median clock and re-centered over non-outlier
   hosts. Feed them to ``analysis/events.merge_event_logs(paths,
   offsets=...)`` before any cross-host join.

2. **Step timeline assembly** (:func:`decompose_step`,
   :func:`assemble_timeline`). Per global step, every host's spans (step
   wall time, collective wire legs incl. the federation's in-slice /
   cross-slice split, snapshot stalls, recompiles, watchdog waits) fold
   into one aligned fleet timeline. The critical path of a lockstep step is
   the slowest host's lane; it decomposes into typed classes (:data:`CLASSES`):
   ``compute``, ``exposed_ici``, ``exposed_dcn``, ``straggler_wait`` (the
   slowest host's excess over the fleet-median lane, attributed BY NAME),
   ``stall`` (checkpoint/compile/dispatch), and ``idle`` (unaccounted
   residual). Classes sum to the step's fleet wall time exactly.

3. **Bounded ledger + detection** (:class:`CritPathLedger`,
   :class:`TimelineRecorder`). A ring of per-step breakdowns with EWMA
   class fractions and trend; each folded step feeds
   ``DetectorBank.note_critpath_step`` so a ``bottleneck_shift`` anomaly
   (dominant class flips, or straggler-wait leaves its band, naming the
   slowest host into the autopilot strike ledger) fires while the run is
   still going. The ledger also cross-checks its measured exposed-collective
   share against ``analysis/hlo_audit.py``'s static prices and the comm
   scheduler's predicted exposed-pct — static-vs-measured disagreement is
   itself a surfaced number (:meth:`TimelineRecorder.crosscheck`).

Surfaces: ``monitor.critpath_report()``, ``GET /debug/critpath`` on the ops
plane, ``thunder_tpu_critpath_fraction{class=}`` gauges, the always-export
``thunder_tpu_critpath_steps_total`` counter, and the committed
``CRITPATH_r*.json`` series written by ``scripts/soak_pod.py`` and gated by
``scripts/perf_report.py --gate``.

Module-top imports are stdlib-only (the recorder sits on the training hot
path; importing it must never drag jax in); events/metrics/detectors are
reached lazily at publish time, mirroring observability/detect.py.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# The typed time classes of one fleet step's critical path, in report order.
CLASSES = (
    "compute",
    "exposed_ici",
    "exposed_dcn",
    "straggler_wait",
    "stall",
    "idle",
)

# Event kinds whose completion is a rendezvous barrier (offset anchors).
_BARRIER_KINDS = ("collective", "hier_all_reduce")


def _median(vals: list) -> float:
    """True median (even lists average the middle pair) — the same
    convention as HostHealthAccumulator.spread, so a 2-host fleet's slow
    half cannot be its own baseline."""
    vs = sorted(vals)
    if not vs:
        return 0.0
    mid = len(vs) // 2
    return vs[mid] if len(vs) % 2 else 0.5 * (vs[mid - 1] + vs[mid])


# =============================================================================
# Clock alignment
# =============================================================================


@dataclass
class SkewEstimate:
    """One host's clock offset vs the (re-centered) fleet-median clock.

    ``offset_s`` > 0 means this host's clock runs AHEAD of the fleet:
    subtract it from the host's timestamps before any cross-host join.
    ``mad_s`` is the median absolute residual across barrier samples — the
    estimator's own consistency check; ``confidence`` shrinks with few
    samples or wide residuals; ``outlier`` flags a host whose residuals are
    too wide for its offset to mean anything (an unstable clock)."""

    host: Any
    offset_s: float
    mad_s: float
    samples: int
    confidence: float
    drift_s_per_s: float = 0.0
    outlier: bool = False

    def as_dict(self) -> dict:
        return {
            "host": self.host,
            "offset_s": round(self.offset_s, 6),
            "mad_s": round(self.mad_s, 6),
            "samples": self.samples,
            "confidence": round(self.confidence, 4),
            "drift_s_per_s": round(self.drift_s_per_s, 9),
            "outlier": self.outlier,
        }


def collect_offset_samples(records) -> dict:
    """``{host: [(barrier_ts, offset_sample_s), ...]}`` from barrier-kind
    records. Records are grouped by ``(kind, fn, cid)`` (``cid`` falls back
    to ``step``); a group with ≥2 hosts yields, per host, ``host ts − group
    median ts``. The first record per host per group wins (a retried
    collective is a different rendezvous, not a better sample)."""
    groups: dict[tuple, dict] = {}
    for rec in records:
        if not isinstance(rec, dict) or rec.get("kind") not in _BARRIER_KINDS:
            continue
        host = rec.get("host")
        cid = rec.get("cid", rec.get("step"))
        try:
            ts = float(rec.get("ts"))
        except (TypeError, ValueError):
            continue
        if host is None or cid is None:
            continue
        key = (rec.get("kind"), rec.get("fn"), cid)
        groups.setdefault(key, {}).setdefault(host, ts)
    samples: dict[Any, list] = {}
    for per_host in groups.values():
        if len(per_host) < 2:
            continue
        ref = _median(list(per_host.values()))
        for host, ts in per_host.items():
            samples.setdefault(host, []).append((ref, ts - ref))
    return samples


def _drift_slope(pairs: list) -> float:
    """Least-squares slope of offset vs barrier time (s of skew per s of
    wall clock) — 0 with <4 samples or a degenerate time span."""
    if len(pairs) < 4:
        return 0.0
    ts = [t for t, _ in pairs]
    xs = [x for _, x in pairs]
    tm = sum(ts) / len(ts)
    xm = sum(xs) / len(xs)
    den = sum((t - tm) ** 2 for t in ts)
    if den <= 1e-9:
        return 0.0
    return sum((t - tm) * (x - xm) for t, x in zip(ts, xs)) / den


def estimate_skew(
    records,
    *,
    min_samples: int = 3,
    outlier_mad_s: float = 0.05,
    full_confidence_samples: int = 8,
) -> dict:
    """Per-host :class:`SkewEstimate` from barrier rendezvous records.

    Robust by construction: the per-barrier reference is the median host
    timestamp (one wild clock cannot drag it), the per-host offset is the
    median of its samples, and ``mad_s`` (median absolute residual) both
    feeds the confidence and flags outliers (``mad_s > outlier_mad_s`` —
    the clock is inconsistent barrier-to-barrier, so no constant offset
    describes it). Offsets are re-centered so the median non-outlier host
    sits at 0. Hosts with fewer than ``min_samples`` barriers are omitted."""
    raw = collect_offset_samples(records)
    ests: dict[Any, SkewEstimate] = {}
    for host, pairs in raw.items():
        if len(pairs) < min_samples:
            continue
        offs = [x for _, x in pairs]
        med = _median(offs)
        mad = _median([abs(x - med) for x in offs])
        outlier = mad > outlier_mad_s
        confidence = min(len(pairs), full_confidence_samples) / float(
            full_confidence_samples
        )
        confidence /= 1.0 + mad / max(outlier_mad_s, 1e-9)
        ests[host] = SkewEstimate(
            host=host,
            offset_s=med,
            mad_s=mad,
            samples=len(pairs),
            confidence=confidence,
            drift_s_per_s=_drift_slope(pairs),
            outlier=outlier,
        )
    good = [e.offset_s for e in ests.values() if not e.outlier]
    center = _median(good) if good else 0.0
    for e in ests.values():
        e.offset_s -= center
    return ests


def offsets_for_merge(estimates: dict) -> dict:
    """The plain ``{host: offset_s}`` map ``merge_event_logs(offsets=...)``
    takes (outlier hosts included: a shifted ordering beats an unshifted
    one even when the offset is noisy)."""
    return {h: e.offset_s for h, e in estimates.items()}


def apply_offsets(records, offsets: dict) -> list:
    """Copies of ``records`` with each host's offset subtracted from ``ts``
    — the cross-host join happens on aligned time, never raw clocks."""
    out = []
    for rec in records:
        if isinstance(rec, dict):
            off = offsets.get(rec.get("host"))
            if off:
                try:
                    rec = dict(rec, ts=float(rec["ts"]) - off)
                except (KeyError, TypeError, ValueError):
                    pass
        out.append(rec)
    return out


# =============================================================================
# Step decomposition
# =============================================================================


@dataclass
class StepBreakdown:
    """One fleet step's critical path, decomposed into :data:`CLASSES`.
    ``classes`` sums to ``total_s`` (the slowest host's lane = the step's
    fleet wall time under lockstep collectives)."""

    step: int
    total_s: float
    classes: dict = field(default_factory=dict)
    slowest_host: Any = None
    n_hosts: int = 0

    def fractions(self) -> dict:
        t = self.total_s
        return {c: (v / t if t > 0 else 0.0) for c, v in self.classes.items()}

    def dominant(self) -> Optional[str]:
        if not self.classes:
            return None
        return max(self.classes, key=lambda c: self.classes[c])

    def as_dict(self) -> dict:
        return {
            "step": self.step,
            "total_s": round(self.total_s, 6),
            "classes": {c: round(v, 6) for c, v in self.classes.items()},
            "slowest_host": self.slowest_host,
            "n_hosts": self.n_hosts,
        }


def decompose_step(step: int, host_spans: dict) -> Optional[StepBreakdown]:
    """Fold per-host spans for one global step into a critical-path
    breakdown.

    ``host_spans``: ``{host: {"total_s": wall seconds (required),
    "ici_s"/"dcn_s"/"stall_s"/"compute_s": typed seconds (optional)}}``.
    The slowest host's lane is the critical path: ``straggler_wait`` is its
    excess over the fleet-median lane (what every other host spends blocked
    at the next collective), and the median-lane budget splits into the
    slowest host's typed spans. When ``compute_s`` is measured, the
    unaccounted remainder is ``idle``; otherwise compute absorbs it (typed
    spans are capped, proportionally, at the budget — accounting must sum
    to the wall time). None when no host reported a positive total."""
    totals = {}
    for host, sp in host_spans.items():
        try:
            t = float(sp["total_s"])
        except (KeyError, TypeError, ValueError):
            continue
        if t > 0:
            totals[host] = t
    if not totals:
        return None
    slowest = max(totals, key=lambda h: totals[h])
    total = totals[slowest]
    median = _median(list(totals.values()))
    straggler = max(0.0, total - median)
    budget = total - straggler  # the median-lane window
    sp = host_spans.get(slowest) or {}

    def span(key):
        try:
            return max(0.0, float(sp.get(key) or 0.0))
        except (TypeError, ValueError):
            return 0.0

    ici, dcn, stall = span("ici_s"), span("dcn_s"), span("stall_s")
    compute = span("compute_s") if sp.get("compute_s") is not None else None
    typed = ici + dcn + stall + (compute or 0.0)
    if typed > budget > 0:
        scale = budget / typed
        ici, dcn, stall = ici * scale, dcn * scale, stall * scale
        if compute is not None:
            compute *= scale
        typed = budget
    if compute is None:
        compute = max(0.0, budget - ici - dcn - stall)
        idle = 0.0
    else:
        idle = max(0.0, budget - typed)
    return StepBreakdown(
        step=int(step),
        total_s=total,
        classes={
            "compute": compute,
            "exposed_ici": ici,
            "exposed_dcn": dcn,
            "straggler_wait": straggler,
            "stall": stall,
            "idle": idle,
        },
        slowest_host=slowest,
        n_hosts=len(totals),
    )


# =============================================================================
# Bounded ledger
# =============================================================================


class CritPathLedger:
    """Bounded ring of :class:`StepBreakdown` + EWMA class fractions.

    Per class it tracks a fast EWMA (the live fraction the gauges export)
    and a slow EWMA; ``trend()`` is fast − slow per class, so a class
    *taking over* shows positive before the dominant flip lands. Locked:
    the recorder folds from the training thread while /debug/critpath
    snapshots from the ops server thread."""

    def __init__(self, capacity: int = 512, alpha: float = 0.2):
        self.ring: deque = deque(maxlen=int(capacity))
        self.alpha = float(alpha)
        self.steps = 0
        self._fast: dict[str, float] = {}
        self._slow: dict[str, float] = {}
        self._totals: dict[str, float] = {}
        self._straggler_hosts: dict[Any, int] = {}
        self._lock = threading.Lock()

    def fold(self, bd: StepBreakdown) -> None:
        fr = bd.fractions()
        with self._lock:
            self.ring.append(bd)
            self.steps += 1
            for c, f in fr.items():
                prev = self._fast.get(c)
                self._fast[c] = f if prev is None else prev + self.alpha * (f - prev)
                prev = self._slow.get(c)
                slow_a = self.alpha * 0.25
                self._slow[c] = f if prev is None else prev + slow_a * (f - prev)
                self._totals[c] = self._totals.get(c, 0.0) + bd.classes.get(c, 0.0)
            if bd.classes.get("straggler_wait", 0.0) > 0 and bd.slowest_host is not None:
                self._straggler_hosts[bd.slowest_host] = (
                    self._straggler_hosts.get(bd.slowest_host, 0) + 1
                )

    def fractions(self) -> dict:
        with self._lock:
            return dict(self._fast)

    def trend(self) -> dict:
        with self._lock:
            return {
                c: self._fast[c] - self._slow.get(c, self._fast[c])
                for c in self._fast
            }

    def dominant(self) -> Optional[str]:
        fr = self.fractions()
        return max(fr, key=fr.get) if fr else None

    def totals(self) -> dict:
        with self._lock:
            return dict(self._totals)

    def snapshot(self, last: int = 8) -> dict:
        with self._lock:
            ring = list(self.ring)
            out = {
                "steps": self.steps,
                "fractions": {c: round(f, 4) for c, f in self._fast.items()},
                "trend": {
                    c: round(self._fast[c] - self._slow.get(c, self._fast[c]), 4)
                    for c in self._fast
                },
                "totals_s": {c: round(v, 6) for c, v in self._totals.items()},
                "straggler_hosts": dict(self._straggler_hosts),
            }
        out["dominant"] = (
            max(out["fractions"], key=out["fractions"].get)
            if out["fractions"] else None
        )
        out["last_steps"] = [bd.as_dict() for bd in ring[-last:]]
        return out

    def format(self) -> str:
        snap = self.snapshot()
        lines = [
            f"critical path over {snap['steps']} fleet steps "
            f"(dominant: {snap['dominant']})",
            f"  {'class':<16} {'ewma_frac':>10} {'trend':>8} {'total_s':>10}",
        ]
        for c in CLASSES:
            if c not in snap["fractions"]:
                continue
            lines.append(
                f"  {c:<16} {snap['fractions'][c]:>10.3f} "
                f"{snap['trend'][c]:>+8.3f} {snap['totals_s'].get(c, 0.0):>10.4f}"
            )
        if snap["straggler_hosts"]:
            worst = max(snap["straggler_hosts"], key=snap["straggler_hosts"].get)
            lines.append(
                f"  straggler-wait attributed to: {worst} "
                f"({snap['straggler_hosts'][worst]}/{snap['steps']} steps)"
            )
        return "\n".join(lines)


# =============================================================================
# The in-loop recorder
# =============================================================================


class TimelineRecorder:
    """The live half of the ledger: fleet drivers feed it per-step spans
    and per-barrier collective records; it folds breakdowns, exports the
    gauges, emits ``critpath_step``/``collective`` events, and streams
    class fractions into ``DetectorBank.note_critpath_step``.

    ``emulated_skew_s`` injects known per-host clock offsets onto emitted
    barrier timestamps — an emulated single-process fleet shares one clock,
    so without injection the alignment loop would be vacuously correct; with
    it, the estimator must *recover* the injected offsets, and the soak gate
    asserts the recovery error (a falsifiable instrument, not a tautology).
    ``host_label`` maps span keys to the suspect-host spelling the
    autopilot strike ledger uses (the federated driver passes
    ``lambda s: f"slice{s}"`` to match ``slice_spread``).

    Skew estimates are recomputed lazily (a dirty flag set per barrier
    record, resolved at report/debug/health time) so the per-step hot-path
    cost stays O(classes).

    ``event_sample`` duty-cycles the *emitted* side only: ``collective`` /
    ``critpath_step`` events and the gauge export fire for 1-in-N
    rendezvous/step ids (deterministic by id, so a sampled barrier is
    sampled on EVERY host and offline alignment groups stay complete).
    The in-process estimator, ledger, and detector feed always see every
    barrier and every step — sampling trades offline log density for
    hot-path cost at scale, never measurement fidelity."""

    def __init__(
        self,
        *,
        capacity: int = 512,
        alpha: float = 0.2,
        bank=None,
        emit_events: bool = True,
        event_sample: int = 1,
        emulated_skew_s: Optional[dict] = None,
        host_label: Optional[Callable[[Any], str]] = None,
        skew_min_samples: int = 3,
        skew_outlier_mad_s: float = 0.05,
        max_skew_groups: int = 256,
        static_exposed_pct: Optional[float] = None,
        predicted_exposed_pct: Optional[float] = None,
    ):
        self.ledger = CritPathLedger(capacity=capacity, alpha=alpha)
        self.bank = bank
        self.emit_events = bool(emit_events)
        self.event_sample = max(1, int(event_sample))
        self.emulated_skew_s = dict(emulated_skew_s or {})
        self._label = host_label or str
        self.skew_min_samples = int(skew_min_samples)
        self.skew_outlier_mad_s = float(skew_outlier_mad_s)
        self.static_exposed_pct = static_exposed_pct
        self.predicted_exposed_pct = predicted_exposed_pct
        self._wire_fracs = (0.0, 0.0)  # (ici, dcn) static shares of compute work
        self._groups: deque = deque(maxlen=int(max_skew_groups))
        self._open: dict[tuple, dict] = {}
        self._hosts_seen: set = set()
        self._skew: dict = {}
        self._skew_dirty = False
        self._lock = threading.Lock()

    def _sampled(self, key) -> bool:
        """Deterministic 1-in-``event_sample`` pick by rendezvous/step id —
        id-keyed (not call-counted) so every host agrees on which barriers
        get emitted and offline groups stay complete. Non-integer ids are
        always emitted (no cross-host-stable hash for them)."""
        if self.event_sample == 1:
            return True
        try:
            return int(key) % self.event_sample == 0
        except (TypeError, ValueError):
            return True

    # -- static wire pricing ---------------------------------------------------

    def set_static_wire(
        self,
        ici_frac: float,
        dcn_frac: float,
        *,
        static_exposed_pct: Optional[float] = None,
    ) -> None:
        """Install the HLO auditor's static wire split: per-tier shares of
        one step's work the driver uses to charge ``exposed_ici`` /
        ``exposed_dcn`` when per-leg measurements are unavailable (the
        emulated fleet), plus the static exposed-pct the cross-check
        compares the measured ledger against."""
        self._wire_fracs = (max(0.0, float(ici_frac)), max(0.0, float(dcn_frac)))
        if static_exposed_pct is not None:
            self.static_exposed_pct = float(static_exposed_pct)

    def static_spans(self, work_s: float) -> dict:
        """Split ``work_s`` of one host's compute-step time by the static
        wire fractions: ``{"ici_s", "dcn_s", "compute_s"}``."""
        ici_f, dcn_f = self._wire_fracs
        ici = work_s * ici_f
        dcn = work_s * dcn_f
        return {
            "ici_s": ici,
            "dcn_s": dcn,
            "compute_s": max(0.0, work_s - ici - dcn),
        }

    # -- barrier records (clock-alignment anchors) -----------------------------

    def note_collective(
        self,
        host: Any,
        cid: Any,
        *,
        fn: str = "train_step",
        s: float = 0.0,
        in_slice_s: float = 0.0,
        cross_slice_s: float = 0.0,
        step: Optional[int] = None,
    ) -> None:
        """One host's completion of rendezvous ``(fn, cid)``. The emitted
        ``collective`` event's ``ts`` carries the host's (possibly
        emulated-skewed) clock; the sample feeds the in-process skew
        estimator."""
        ts = time.time() + float(self.emulated_skew_s.get(host, 0.0))
        with self._lock:
            self._hosts_seen.add(host)
            key = (fn, cid)
            group = self._open.get(key)
            if group is None:
                group = self._open[key] = {}
                while len(self._open) > 8:
                    oldest = next(iter(self._open))
                    self._groups.append(self._open.pop(oldest))
            group.setdefault(host, ts)
            self._skew_dirty = True
        if self.emit_events and self._sampled(cid):
            try:
                from thunder_tpu.observability.events import emit_event

                fields = {
                    "fn": fn, "cid": cid, "s": round(float(s), 6),
                    "host": host, "ts": ts,
                }
                if in_slice_s:
                    fields["in_slice_s"] = round(float(in_slice_s), 6)
                if cross_slice_s:
                    fields["cross_slice_s"] = round(float(cross_slice_s), 6)
                if step is not None:
                    fields["step"] = int(step)
                emit_event("collective", **fields)
            except Exception:
                pass

    def skew_estimates(self) -> dict:
        """Per-host :class:`SkewEstimate` over the barrier samples seen so
        far (lazily recomputed)."""
        with self._lock:
            if not self._skew_dirty:
                return dict(self._skew)
            groups = list(self._groups) + list(self._open.values())
            self._skew_dirty = False
        records = []
        for i, per_host in enumerate(groups):
            for host, ts in per_host.items():
                records.append(
                    {"kind": "collective", "fn": "_", "cid": i, "host": host,
                     "ts": ts}
                )
        ests = estimate_skew(
            records,
            min_samples=self.skew_min_samples,
            outlier_mad_s=self.skew_outlier_mad_s,
        )
        with self._lock:
            self._skew = ests
        try:
            from thunder_tpu.observability import metrics as obsm

            if obsm.enabled():
                for h, e in ests.items():
                    obsm.CRITPATH_SKEW_MS.set(
                        e.offset_s * 1e3, host=self._label(h)
                    )
        except Exception:
            pass
        return dict(ests)

    # -- per-step fold ---------------------------------------------------------

    def record_step(self, step: int, host_spans: dict) -> Optional[StepBreakdown]:
        """Fold one fleet step (``host_spans`` as in :func:`decompose_step`)
        into the ledger; export gauges, emit the ``critpath_step`` event,
        and stream fractions into the detector bank. Returns the breakdown
        (None when no host reported)."""
        bd = decompose_step(step, host_spans)
        if bd is None:
            return None
        with self._lock:
            self._hosts_seen.update(host_spans)
        self.ledger.fold(bd)
        fractions = bd.fractions()
        slowest = self._label(bd.slowest_host)
        sampled = self._sampled(step)
        try:
            from thunder_tpu.observability import metrics as obsm

            obsm.CRITPATH_STEPS.inc_always()
            if sampled and obsm.enabled():
                # EWMA fractions change slowly vs any scrape interval, so
                # the gauge refresh rides the same duty cycle as events.
                for c, f in self.ledger.fractions().items():
                    obsm.CRITPATH_FRACTION.set(f, **{"class": c})
        except Exception:
            pass
        if self.emit_events and sampled:
            try:
                from thunder_tpu.observability.events import emit_event

                emit_event(
                    "critpath_step",
                    step=bd.step,
                    total_s=round(bd.total_s, 6),
                    classes={c: round(v, 6) for c, v in bd.classes.items()},
                    slowest_host=slowest,
                    n_hosts=bd.n_hosts,
                )
            except Exception:
                pass
        if self.bank is not None:
            try:
                self.bank.note_critpath_step(
                    bd.step, fractions, slowest_host=slowest
                )
            except Exception:
                pass
        return bd

    # -- cross-checks and reporting --------------------------------------------

    def measured_exposed_pct(self) -> Optional[float]:
        """Exposed-collective share of the critical path's *working* time
        (compute + exposed wire; straggler/stall/idle excluded so the
        number is commensurable with the HLO auditor's static
        ``exposed_pct`` and the comm scheduler's prediction)."""
        fr = self.ledger.fractions()
        wire = fr.get("exposed_ici", 0.0) + fr.get("exposed_dcn", 0.0)
        denom = fr.get("compute", 0.0) + wire
        if denom <= 0:
            return None
        return 100.0 * wire / denom

    def crosscheck(self) -> dict:
        """Static-vs-measured exposed-collective disagreement, surfaced as
        numbers: the measured ledger share vs the HLO auditor's static
        price and the comm scheduler's predicted exposed-pct."""
        measured = self.measured_exposed_pct()
        out: dict[str, Any] = {
            "measured_exposed_pct": None if measured is None else round(measured, 3)
        }
        if self.static_exposed_pct is not None:
            out["static_exposed_pct"] = round(self.static_exposed_pct, 3)
            if measured is not None:
                out["delta_static_pct"] = round(measured - self.static_exposed_pct, 3)
        if self.predicted_exposed_pct is not None:
            out["predicted_exposed_pct"] = round(self.predicted_exposed_pct, 3)
            if measured is not None:
                out["delta_predicted_pct"] = round(
                    measured - self.predicted_exposed_pct, 3
                )
        return out

    def health_state(self) -> dict:
        """The /healthz ``timeline`` component's raw state: host count,
        folded steps, and the weakest non-outlier alignment confidence."""
        ests = self.skew_estimates()
        with self._lock:
            hosts = len(self._hosts_seen)
        good = [e.confidence for e in ests.values() if not e.outlier]
        return {
            "enabled": True,
            "hosts": hosts,
            "steps": self.ledger.steps,
            "min_confidence": round(min(good), 4) if good else None,
            "outlier_hosts": sorted(
                (self._label(h) for h, e in ests.items() if e.outlier), key=str
            ),
        }

    def debug_state(self) -> dict:
        """The ``GET /debug/critpath`` payload."""
        out = {
            "enabled": True,
            "ledger": self.ledger.snapshot(),
            "skew": {
                self._label(h): e.as_dict()
                for h, e in sorted(
                    self.skew_estimates().items(), key=lambda kv: str(kv[0])
                )
            },
            "crosscheck": self.crosscheck(),
        }
        out["health"] = self.health_state()
        return out

    def format_report(self) -> str:
        """The printable spelling of /debug/critpath: ledger table + skew
        estimates + the static-vs-measured cross-check."""
        lines = [self.ledger.format()]
        ests = self.skew_estimates()
        if ests:
            lines.append("  clock skew (vs fleet-median clock):")
            for h, e in sorted(ests.items(), key=lambda kv: str(kv[0])):
                flag = "  OUTLIER" if e.outlier else ""
                lines.append(
                    f"    {self._label(h):<10} offset {e.offset_s * 1e3:+8.2f} ms"
                    f"  mad {e.mad_s * 1e3:6.2f} ms  conf {e.confidence:.2f}"
                    f"  n={e.samples}{flag}"
                )
        cc = self.crosscheck()
        if cc.get("measured_exposed_pct") is not None:
            parts = [f"measured {cc['measured_exposed_pct']:.1f}%"]
            if "static_exposed_pct" in cc:
                parts.append(
                    f"static {cc['static_exposed_pct']:.1f}% "
                    f"(Δ {cc.get('delta_static_pct', 0.0):+.1f})"
                )
            if "predicted_exposed_pct" in cc:
                parts.append(
                    f"scheduler {cc['predicted_exposed_pct']:.1f}% "
                    f"(Δ {cc.get('delta_predicted_pct', 0.0):+.1f})"
                )
            lines.append("  exposed-collective: " + ", ".join(parts))
        return "\n".join(lines)


# =============================================================================
# Offline assembly (merged logs -> breakdowns)
# =============================================================================


def assemble_timeline(
    records,
    *,
    skew: Optional[dict] = None,
    min_skew_samples: int = 3,
    outlier_mad_s: float = 0.05,
) -> tuple:
    """Offline twin of the recorder: fold merged (or to-be-merged) event
    records into per-step breakdowns. Estimates per-host skew from the
    barrier records (unless ``skew`` supplies estimates), aligns timestamps,
    then assembles per-step host spans from ``step_time`` (wall),
    ``collective`` (wire legs), ``snapshot`` (stall), recompile
    ``compile_end`` and ``collective_timeout`` (stall at the host's last
    seen step). Returns ``(breakdowns, skew_estimates)``."""
    recs = [r for r in records if isinstance(r, dict)]
    ests = skew if skew is not None else estimate_skew(
        recs, min_samples=min_skew_samples, outlier_mad_s=outlier_mad_s
    )
    if ests:
        recs = apply_offsets(recs, offsets_for_merge(ests))
    spans: dict[int, dict] = {}
    last_step: dict[Any, int] = {}

    def span(step, host):
        return spans.setdefault(int(step), {}).setdefault(
            host, {"total_s": 0.0, "ici_s": 0.0, "dcn_s": 0.0, "stall_s": 0.0}
        )

    def fnum(v):
        try:
            return float(v)
        except (TypeError, ValueError):
            return 0.0

    for rec in recs:
        kind = rec.get("kind")
        host = rec.get("host")
        if kind == "step_time" and rec.get("step") is not None:
            sp = span(rec["step"], host)
            sp["total_s"] += fnum(rec.get("s"))
            last_step[host] = int(rec["step"])
        elif kind in _BARRIER_KINDS:
            step = rec.get("step", rec.get("cid"))
            if step is None:
                continue
            try:
                step = int(step)
            except (TypeError, ValueError):
                continue
            sp = span(step, host)
            in_s = fnum(rec.get("in_slice_s"))
            cross_s = fnum(rec.get("cross_slice_s"))
            if not in_s and not cross_s:
                in_s = fnum(rec.get("s"))
            sp["ici_s"] += in_s
            sp["dcn_s"] += cross_s
            last_step[host] = step
        elif kind == "snapshot" and rec.get("step") is not None:
            span(rec["step"], host)["stall_s"] += fnum(rec.get("stall_ms")) / 1e3
        elif kind == "compile_end" and rec.get("recompile"):
            if host in last_step:
                span(last_step[host], host)["stall_s"] += fnum(rec.get("ms")) / 1e3
        elif kind == "collective_timeout":
            if host in last_step:
                span(last_step[host], host)["stall_s"] += fnum(rec.get("timeout_s"))
    breakdowns = []
    for step in sorted(spans):
        bd = decompose_step(step, spans[step])
        if bd is not None:
            breakdowns.append(bd)
    return breakdowns, ests


def ledger_from_records(records, **kw) -> tuple:
    """Fold :func:`assemble_timeline`'s breakdowns into a fresh
    :class:`CritPathLedger` — the lint smoke's offline path. Returns
    ``(ledger, breakdowns, skew_estimates)``."""
    breakdowns, ests = assemble_timeline(records, **kw)
    ledger = CritPathLedger()
    for bd in breakdowns:
        ledger.fold(bd)
    return ledger, breakdowns, ests


# =============================================================================
# Static wire-tier split (HLO auditor join)
# =============================================================================


def split_static_wire(sites, devices_per_slice: int) -> dict:
    """Split an ``HloScheduleReport``'s collective sites into interconnect
    tiers by replica-group size: a group that fits inside one slice rides
    ICI, a larger (or unknown-size) group crosses the DCN. A group of
    exactly ``devices_per_slice`` devices *could* be a cross-slice DP group
    of the same cardinality — the heuristic charges it to ICI
    (conservative: understates DCN), which the cross-check's delta then
    carries as measurement disagreement rather than hiding. Returns wire
    microseconds and fractions per tier."""
    dps = max(1, int(devices_per_slice))
    ici_us = dcn_us = 0.0
    for site in sites:
        wire = float(getattr(site, "wire_us", 0.0) or 0.0)
        size = getattr(site, "group_size", None)
        if size is not None and int(size) <= dps:
            ici_us += wire
        else:
            dcn_us += wire
    total = ici_us + dcn_us
    return {
        "ici_us": round(ici_us, 3),
        "dcn_us": round(dcn_us, 3),
        "ici_frac": round(ici_us / total, 6) if total else 0.0,
        "dcn_frac": round(dcn_us / total, 6) if total else 0.0,
    }


# =============================================================================
# Module lifecycle (the roofline pattern: one process-wide recorder)
# =============================================================================

_state: dict = {"recorder": None}


def current() -> Optional[TimelineRecorder]:
    return _state["recorder"]


def enable(**options) -> TimelineRecorder:
    """Install the process-wide recorder (options forward to
    :class:`TimelineRecorder`). Installing a DetectorBank-armed recorder is
    how ``bottleneck_shift`` reaches the autopilot."""
    rec = TimelineRecorder(**options)
    _state["recorder"] = rec
    return rec


def disable() -> None:
    _state["recorder"] = None


def debug_state() -> dict:
    rec = current()
    return rec.debug_state() if rec is not None else {"enabled": False}


def health_state() -> Optional[dict]:
    rec = current()
    return rec.health_state() if rec is not None else None
