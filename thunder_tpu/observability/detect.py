"""Streaming anomaly detection: the ONLINE half of the telemetry pipeline.

Every observability layer before this one is post-hoc — JSONL logs merged
offline by ``lint_traces --events``, regressions caught at bench-gate time.
This module turns the same signal streams into *live* verdicts (ISSUE 15):
incremental EWMA/CUSUM detectors over step time, recompile rate, goodput,
and per-host spread that fire while the run is still going, so a drifting
straggler or a recompile storm climbs the autopilot's hysteresis ladder
*before* a watchdog timeout names it.

Pieces:

- :class:`EwmaStat` / :class:`CusumDetector` / :class:`DriftDetector` /
  :class:`RateDetector` — the incremental statistics, one value at a time,
  O(1) memory;
- :class:`HostHealthAccumulator` — per-host step stats + fleet spread,
  factored OUT of the offline ``analysis/events.host_health`` (which now
  builds on it, byte-identical on merged-log goldens) and reused here as
  the online spread detector's state (the ISSUE 15 satellite);
- :class:`DetectorBank` — the event-tap consumer the ops plane installs
  (``observability/opsplane.enable``): it watches ``step_time`` and
  ``compile_end`` records flow past and raises typed ``anomaly`` events
  (kind, severity, value, baseline, evidence window), bumps
  ``thunder_tpu_anomalies_total{kind=}``, and routes each anomaly into the
  installed :class:`~thunder_tpu.resilience.autopilot.Autopilot` via
  ``note_anomaly`` — a first-class policy signal, not a log line.

Anomaly kinds (docs/observability.md "ops plane" lists the knobs):

=================  ==========================================================
step_time_drift    CUSUM over per-step seconds: sustained positive drift
                   past ``cusum_threshold`` normalized sigmas (a straggler
                   developing, GC pressure, a slowing device)
goodput_drop       fast-EWMA step time over slow-EWMA baseline exceeds
                   ``goodput_drop_factor`` for ``goodput_consecutive``
                   samples — throughput (tokens/step-second) sagging
recompile_storm    ≥ ``recompile_threshold`` recompile ``compile_end``
                   records inside ``recompile_window_s`` — guards churning
                   or a de-opt ladder thrashing (the online twin of the
                   replay's ``events.recompile-storm``)
host_spread        slowest host mean / fleet median past
                   ``spread_threshold`` with ≥2 hosts reporting — the
                   incremental form of ``host_health``'s straggler flag
slice_spread       slowest slice mean / cross-slice median past
                   ``slice_spread_threshold`` with ≥2 slices reporting —
                   the DCN-tier twin of ``host_spread`` (ISSUE 18): one
                   whole slice lagging the federation behind its DCN link,
                   attributed as ``suspect_host="slice<N>"`` so the
                   autopilot's strike ledger accumulates against the slice
bottleneck_shift   the fleet critical path's dominant time class flipped
                   (compute ↔ exposed wire ↔ straggler-wait ...), or the
                   straggler-wait fraction left its band for consecutive
                   steps — fed per step by the timeline recorder (ISSUE
                   20), naming the slowest host so the strike ledger
                   accumulates against it
=================  ==========================================================

Module-top imports are stdlib-only (the bank is installed from the event
path; importing it must never drag jax in); events/metrics/autopilot are
imported lazily at anomaly time.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

SEVERITIES = ("info", "warn", "critical")


# =============================================================================
# Incremental statistics
# =============================================================================


class EwmaStat:
    """Exponentially-weighted mean + variance, one float at a time."""

    __slots__ = ("alpha", "mean", "var", "n")

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self.mean = 0.0
        self.var = 0.0
        self.n = 0

    def update(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if self.n == 1:
            self.mean = x
            self.var = 0.0
            return
        d = x - self.mean
        self.mean += self.alpha * d
        # West's EWM variance: decays old spread, charges the new deviation.
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * d * d)

    def sigma(self, *, rel_floor: float = 0.05) -> float:
        """Std-dev estimate, floored at ``rel_floor``×mean so a perfectly
        steady warm-up cannot make every later jitter look infinitely
        anomalous."""
        return max(math.sqrt(max(self.var, 0.0)), abs(self.mean) * rel_floor, 1e-12)


class CusumDetector:
    """One-sided (high) CUSUM over sigma-normalized deviations.

    ``update(x)`` returns an evidence dict when the cumulative sum of
    ``(x - mean)/sigma - drift`` exceeds ``threshold`` — a sustained upward
    drift, not a single spike. The baseline EWMA is FROZEN while a sample
    deviates past ``freeze_k`` sigmas (an anomaly must not teach the
    baseline that slow is normal), the sum resets after firing, and a
    ``cooldown`` of samples must pass before the detector re-arms — one
    drift raises one anomaly (then a periodic re-alert if it persists),
    not one per subsequent slow step."""

    __slots__ = ("stat", "drift", "threshold", "min_samples", "freeze_k",
                 "cooldown", "cusum", "window", "_quiet")

    def __init__(self, *, alpha: float = 0.2, drift: float = 0.5,
                 threshold: float = 6.0, min_samples: int = 8,
                 freeze_k: float = 4.0, cooldown: int = 16, window: int = 8):
        self.stat = EwmaStat(alpha)
        self.drift = float(drift)
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.freeze_k = float(freeze_k)
        self.cooldown = int(cooldown)
        self.cusum = 0.0
        self.window: deque = deque(maxlen=int(window))
        self._quiet = 0

    def update(self, x: float) -> Optional[dict]:
        x = float(x)
        self.window.append(x)
        if self.stat.n < self.min_samples:
            self.stat.update(x)
            return None
        sigma = self.stat.sigma()
        z = (x - self.stat.mean) / sigma
        if z < self.freeze_k:
            self.stat.update(x)
        if self._quiet > 0:
            self._quiet -= 1
            return None
        self.cusum = max(0.0, self.cusum + z - self.drift)
        if self.cusum <= self.threshold:
            return None
        out = {
            "value": x,
            "baseline": self.stat.mean,
            "cusum": round(self.cusum, 3),
            "window": [round(v, 6) for v in self.window],
        }
        self.cusum = 0.0
        self._quiet = self.cooldown
        return out


class DriftDetector:
    """Sustained-ratio detector: a fast EWMA tracking the recent level
    against a slow EWMA baseline; fires when fast/slow exceeds ``factor``
    for ``consecutive`` samples. The goodput shape: tokens/sec is
    tokens/step-seconds, so a sustained step-time ratio IS an inverse
    throughput ratio, without needing token counts on the stream."""

    __slots__ = ("fast", "slow", "factor", "consecutive", "min_samples",
                 "cooldown", "_hits", "_quiet", "window")

    def __init__(self, *, fast_alpha: float = 0.5, slow_alpha: float = 0.05,
                 factor: float = 1.6, consecutive: int = 4,
                 min_samples: int = 8, cooldown: int = 16, window: int = 8):
        self.fast = EwmaStat(fast_alpha)
        self.slow = EwmaStat(slow_alpha)
        self.factor = float(factor)
        self.consecutive = int(consecutive)
        self.min_samples = int(min_samples)
        self.cooldown = int(cooldown)
        self._hits = 0
        self._quiet = 0
        self.window: deque = deque(maxlen=int(window))

    def update(self, x: float) -> Optional[dict]:
        x = float(x)
        self.window.append(x)
        self.fast.update(x)
        if self.slow.n < self.min_samples:
            self.slow.update(x)
            return None
        ratio = self.fast.mean / self.slow.mean if self.slow.mean else 0.0
        if ratio < self.factor:
            # Only a healthy sample teaches the baseline: absorbing the
            # degraded level would silently redefine it as normal.
            self.slow.update(x)
            self._hits = 0
            return None
        if self._quiet > 0:
            self._quiet -= 1
            return None
        self._hits += 1
        if self._hits < self.consecutive:
            return None
        self._hits = 0
        self._quiet = self.cooldown
        return {
            "value": self.fast.mean,
            "baseline": self.slow.mean,
            "ratio": round(ratio, 3),
            "window": [round(v, 6) for v in self.window],
        }


class BandDetector:
    """Two-sided ratio-band detector for slow-cadence streams (the roofline
    duty cycle feeds one sample per probe, not per step): a slow EWMA
    baseline; fires when ``value/baseline`` leaves ``[1/factor, factor]``
    for ``consecutive`` samples. Two-sided because both directions are
    verdicts — an op running slower than its history is a kernel
    regression, an op running *faster* than the cost model ever predicted
    means the pricing is stale. Only in-band samples teach the baseline,
    and a fired detector stays quiet for ``cooldown`` samples (sample
    count, not wall clock: at one probe every N steps a time-based
    cooldown would never be reached)."""

    __slots__ = ("slow", "factor", "consecutive", "min_samples",
                 "cooldown", "_hits", "_quiet", "window")

    def __init__(self, *, slow_alpha: float = 0.2, factor: float = 1.5,
                 consecutive: int = 2, min_samples: int = 3,
                 cooldown: int = 16, window: int = 8):
        self.slow = EwmaStat(slow_alpha)
        self.factor = float(factor)
        self.consecutive = int(consecutive)
        self.min_samples = int(min_samples)
        self.cooldown = int(cooldown)
        self._hits = 0
        self._quiet = 0
        self.window: deque = deque(maxlen=int(window))

    def update(self, x: float) -> Optional[dict]:
        x = float(x)
        self.window.append(x)
        if self.slow.n < self.min_samples:
            self.slow.update(x)
            return None
        ratio = x / self.slow.mean if self.slow.mean else 0.0
        if ratio > 0 and (1.0 / self.factor) <= ratio <= self.factor:
            self.slow.update(x)
            self._hits = 0
            return None
        if self._quiet > 0:
            self._quiet -= 1
            return None
        self._hits += 1
        if self._hits < self.consecutive:
            return None
        self._hits = 0
        self._quiet = self.cooldown
        return {
            "value": x,
            "baseline": self.slow.mean,
            "ratio": round(ratio, 3),
            "window": [round(v, 6) for v in self.window],
        }


class RateDetector:
    """Events-per-window threshold (the recompile-storm shape): ``tick(ts)``
    fires when ``threshold`` ticks land inside ``window_s``. The tick
    history clears on firing so one storm raises one anomaly."""

    __slots__ = ("window_s", "threshold", "_ticks")

    def __init__(self, *, window_s: float = 60.0, threshold: int = 4):
        self.window_s = float(window_s)
        self.threshold = int(threshold)
        self._ticks: deque = deque()

    def tick(self, ts: Optional[float] = None) -> Optional[dict]:
        ts = time.time() if ts is None else float(ts)
        self._ticks.append(ts)
        while self._ticks and ts - self._ticks[0] > self.window_s:
            self._ticks.popleft()
        if len(self._ticks) < self.threshold:
            return None
        out = {
            "value": float(len(self._ticks)),
            "baseline": float(self.threshold),
            "window": [round(t, 3) for t in self._ticks],
        }
        self._ticks.clear()
        return out


# =============================================================================
# Host-health accumulator (shared with analysis/events.host_health)
# =============================================================================


class HostHealthAccumulator:
    """Incremental per-host step-time statistics + fleet spread.

    Factored out of ``analysis/events.host_health`` (ISSUE 15 satellite):
    the offline replay feeds it one merged record at a time and reads the
    SAME numbers the old from-scratch recompute produced (running sum in
    record order ⇒ identical floats), while the online spread detector
    (:class:`DetectorBank`) feeds it live ``step_time`` events. O(hosts)
    memory, O(1) per sample."""

    def __init__(self):
        # host -> [steps, sum_s, max_s]; insertion order = first-seen order,
        # which the offline summary's "hosts" dict preserves.
        self._hosts: dict[Any, list] = {}

    def add(self, host: Any, s: float) -> None:
        st = self._hosts.get(host)
        if st is None:
            self._hosts[host] = [1, s, s]
            return
        st[0] += 1
        st[1] += s
        if s > st[2]:
            st[2] = s

    def __len__(self) -> int:
        return len(self._hosts)

    def host_stats(self) -> dict:
        """``{host: {"steps", "mean_s", "max_s"}}`` in first-seen order —
        the exact per-host block of the offline summary."""
        return {
            h: {"steps": n, "mean_s": total / n, "max_s": mx}
            for h, (n, total, mx) in self._hosts.items()
        }

    def spread(self) -> tuple[float, float]:
        """(fleet median of per-host means, slowest mean / median). (0, 0)
        with no hosts. True median — even fleets average the middle pair,
        so a 2-host fleet's slow half cannot be its own baseline."""
        if not self._hosts:
            return 0.0, 0.0
        means = sorted(total / n for n, total, _ in self._hosts.values())
        mid = len(means) // 2
        median = means[mid] if len(means) % 2 else 0.5 * (means[mid - 1] + means[mid])
        return median, (max(means) / median if median else 0.0)


# =============================================================================
# The detector bank (the ops plane's event tap)
# =============================================================================


@dataclass
class DetectorConfig:
    """Tuning knobs (docs/observability.md "ops plane" documents each).
    The defaults are sized for production step cadences; the soak driver
    passes a compressed-timescale config."""

    step_alpha: float = 0.2
    step_cusum_drift: float = 0.5
    step_cusum_threshold: float = 6.0
    min_samples: int = 8
    goodput_drop_factor: float = 1.6
    goodput_consecutive: int = 4
    recompile_window_s: float = 60.0
    recompile_threshold: int = 4
    spread_threshold: float = 1.5
    spread_min_steps: int = 4
    spread_consecutive: int = 3
    # Cross-slice (DCN-tier) spread: lower bar than the in-slice host
    # spread — a whole slice lagging is a federation-level event (ISSUE 18).
    slice_spread_threshold: float = 1.3
    # Roofline duty-cycle streams (ISSUE 19): per-op measured/predicted
    # ratio band. Sized for *rare* samples — one per duty-cycled probe —
    # so the trip thresholds are much lower than the per-step detectors'.
    roofline_band_factor: float = 1.5
    roofline_consecutive: int = 2
    roofline_min_samples: int = 3
    # Fleet critical-path ledger (ISSUE 20): bottleneck_shift fires when
    # the EWMA-dominant time class flips after warmup, or the
    # straggler-wait fraction exceeds its absolute band (naming the slowest
    # host into the autopilot strike ledger).
    critpath_min_steps: int = 6
    critpath_straggler_frac: float = 0.25
    critpath_consecutive: int = 2
    # The critpath feed is per fleet STEP (the spread detectors see one
    # sample per host per step), so its re-arm cadence gets its own knob;
    # None inherits ``cooldown``. 0 = re-alert every ``critpath_consecutive``
    # steps while the band violation persists.
    critpath_cooldown: Optional[int] = None
    # Samples a tripped detector stays quiet before re-arming (one drift =
    # one anomaly, then periodic re-alerts while it persists).
    cooldown: int = 16
    # value/baseline past this ratio upgrades warn -> critical.
    critical_factor: float = 4.0
    max_anomalies: int = 128


@dataclass
class Anomaly:
    """One detector verdict, mirrored into the typed ``anomaly`` event."""

    kind: str
    severity: str
    value: float
    baseline: float
    ts: float
    detector: str
    window: list = field(default_factory=list)
    suspect_host: Optional[Any] = None
    fn: Optional[str] = None

    def as_event_fields(self) -> dict:
        out = {
            "anomaly": self.kind,
            "severity": self.severity,
            "value": round(float(self.value), 6),
            "baseline": round(float(self.baseline), 6),
            "detector": self.detector,
            "window": self.window,
        }
        if self.suspect_host is not None:
            out["suspect_host"] = self.suspect_host
        if self.fn:
            out["fn"] = self.fn
        return out


class DetectorBank:
    """Consumes the ops-plane event tap (``observability/events`` routes
    every emitted record here when the plane is on) and raises anomalies.

    Per ``step_time`` stream (keyed by fn) it runs a CUSUM drift detector
    and a goodput-ratio detector; per ``compile_end{recompile}`` a rate
    detector; per-host step times feed the shared
    :class:`HostHealthAccumulator` for the online spread check. Every
    anomaly is (1) an ``anomaly`` event on the active log, (2) a
    ``thunder_tpu_anomalies_total{kind=}`` bump, (3) a
    ``note_anomaly`` call on the installed autopilot, and (4) kept in a
    bounded ring for ``/healthz`` / ``/debug/state``. Consumption is
    locked (events arrive from the training thread, the checkpoint writer,
    and the watchdog worker) and exception-proof — a detector bug must
    never take the workload down."""

    def __init__(self, config: Optional[DetectorConfig] = None):
        self.config = config or DetectorConfig()
        self._lock = threading.Lock()
        self._step: dict[str, CusumDetector] = {}
        self._goodput: dict[str, DriftDetector] = {}
        self._recompiles = RateDetector(
            window_s=self.config.recompile_window_s,
            threshold=self.config.recompile_threshold,
        )
        self._spread_acc = HostHealthAccumulator()
        self._spread_hits = 0
        self._spread_quiet = 0
        self._slice_acc = HostHealthAccumulator()
        self._slice_hits = 0
        self._slice_quiet = 0
        self._roofline: dict[str, BandDetector] = {}
        self._critpath = {
            "ewma": {}, "steps": 0, "dom": None, "dom_hits": 0,
            "dom_quiet": 0, "strag_hits": 0, "strag_quiet": 0,
        }
        self.anomalies: deque = deque(maxlen=self.config.max_anomalies)
        self.consumed = 0

    # -- the tap ---------------------------------------------------------------

    def consume(self, kind: str, fields: dict) -> None:
        if kind == "anomaly":
            return  # our own output flowing back through the tap
        raised: list[Anomaly] = []
        with self._lock:
            self.consumed += 1
            if kind == "step_time":
                raised = self._on_step(fields)
            elif kind == "compile_end" and fields.get("recompile"):
                raised = self._on_recompile()
        for a in raised:
            self._publish(a)

    # -- per-kind handlers (under the lock) ------------------------------------

    def _on_step(self, fields: dict) -> list:
        cfg = self.config
        try:
            s = float(fields["s"])
        except (KeyError, TypeError, ValueError):
            return []
        fn = str(fields.get("fn") or "step")
        out: list[Anomaly] = []
        det = self._step.get(fn)
        if det is None:
            det = self._step[fn] = CusumDetector(
                alpha=cfg.step_alpha, drift=cfg.step_cusum_drift,
                threshold=cfg.step_cusum_threshold,
                min_samples=cfg.min_samples, cooldown=cfg.cooldown,
            )
        hit = det.update(s)
        if hit:
            out.append(self._anomaly("step_time_drift", "cusum", hit, fn=fn))
        good = self._goodput.get(fn)
        if good is None:
            good = self._goodput[fn] = DriftDetector(
                factor=cfg.goodput_drop_factor,
                consecutive=cfg.goodput_consecutive,
                min_samples=cfg.min_samples, cooldown=cfg.cooldown,
            )
        hit = good.update(s)
        if hit:
            out.append(self._anomaly("goodput_drop", "ewma_ratio", hit, fn=fn))
        out.extend(self._on_spread(fields, s))
        out.extend(self._on_slice_spread(fields, s))
        return out

    def _on_spread(self, fields: dict, s: float) -> list:
        cfg = self.config
        host = fields.get("host")
        if host is None:
            from thunder_tpu.observability.events import host_identity

            host = host_identity()["host"]
        self._spread_acc.add(host, s)
        if len(self._spread_acc) < 2:
            return []
        stats = self._spread_acc.host_stats()
        if min(st["steps"] for st in stats.values()) < cfg.spread_min_steps:
            return []
        median, spread = self._spread_acc.spread()
        if spread <= cfg.spread_threshold:
            self._spread_hits = 0
            return []
        if self._spread_quiet > 0:
            self._spread_quiet -= 1
            return []
        self._spread_hits += 1
        if self._spread_hits < cfg.spread_consecutive:
            return []
        self._spread_hits = 0
        self._spread_quiet = cfg.cooldown
        slow = max(stats, key=lambda h: stats[h]["mean_s"])
        return [self._anomaly(
            "host_spread", "spread",
            {"value": spread, "baseline": cfg.spread_threshold,
             "window": [round(st["mean_s"], 6) for st in stats.values()]},
            suspect_host=slow,
        )]

    def _on_slice_spread(self, fields: dict, s: float) -> list:
        cfg = self.config
        sl = fields.get("slice")
        if sl is None:
            try:
                from thunder_tpu.resilience.chaos import slice_id

                sl = slice_id()
            except Exception:
                return []
        self._slice_acc.add(int(sl), s)
        if len(self._slice_acc) < 2:
            return []
        stats = self._slice_acc.host_stats()
        if min(st["steps"] for st in stats.values()) < cfg.spread_min_steps:
            return []
        median, spread = self._slice_acc.spread()
        if spread <= cfg.slice_spread_threshold:
            self._slice_hits = 0
            return []
        if self._slice_quiet > 0:
            self._slice_quiet -= 1
            return []
        self._slice_hits += 1
        if self._slice_hits < cfg.spread_consecutive:
            return []
        self._slice_hits = 0
        self._slice_quiet = cfg.cooldown
        slow = max(stats, key=lambda h: stats[h]["mean_s"])
        return [self._anomaly(
            "slice_spread", "spread",
            {"value": spread, "baseline": cfg.slice_spread_threshold,
             "window": [round(st["mean_s"], 6) for st in stats.values()]},
            suspect_host=f"slice{slow}",
        )]

    def note_slice_step(self, slice_: int, s: float) -> None:
        """Direct per-slice step-time feed for federated drivers (ISSUE 18):
        the emulated fleet runs every slice in one process, so host-keyed
        ``step_time`` events cannot separate the slices — the driver calls
        this instead with the per-slice wall time (the ``slice_step_time``
        hook of ``run_federated_training``)."""
        raised: list[Anomaly] = []
        with self._lock:
            raised = self._on_slice_spread({"slice": int(slice_)}, float(s))
        for a in raised:
            self._publish(a)

    def note_roofline_op(self, label: str, measured_us: float,
                         roofline_us: float, *,
                         executor: Optional[str] = None) -> None:
        """Direct per-op feed from the roofline sampler (ISSUE 19): each
        duty-cycled probe reports every ledger op's measured device time
        against its static roofline bound. The measured/predicted ratio
        streams into a per-op :class:`BandDetector`; a sustained walk out
        of the band is ``kernel_regression`` when an executor claimed the
        op (a regressed Pallas/custom kernel) and ``cost_model_drift``
        otherwise (the pricing no longer describes the hardware). Direct
        feed, not an event tap: probe joins are already in-process objects
        and the per-op fanout would be noise on the event log."""
        try:
            measured = float(measured_us)
            predicted = float(roofline_us)
        except (TypeError, ValueError):
            return
        if measured <= 0 or predicted <= 0:
            return
        cfg = self.config
        claimed = executor not in (None, "", "jax")
        raised: list[Anomaly] = []
        with self._lock:
            det = self._roofline.get(label)
            if det is None:
                det = self._roofline[label] = BandDetector(
                    factor=cfg.roofline_band_factor,
                    consecutive=cfg.roofline_consecutive,
                    min_samples=cfg.roofline_min_samples,
                    cooldown=cfg.cooldown,
                )
            hit = det.update(measured / predicted)
            if hit:
                kind = "kernel_regression" if claimed else "cost_model_drift"
                raised = [self._anomaly(kind, "roofline_band", hit, fn=label)]
        for a in raised:
            self._publish(a)

    def note_critpath_step(self, step: int, fractions: dict, *,
                           slowest_host: Optional[Any] = None) -> None:
        """Direct per-step feed from the fleet timeline recorder (ISSUE
        20): class fractions of one step's critical path. Two triggers
        raise ``bottleneck_shift``:

        - the EWMA-dominant class flips after ``critpath_min_steps`` warmup
          (``fn`` carries ``old->new``; fleet-level, so no suspect host —
          any relevant autopilot decision may cite it);
        - the straggler-wait fraction exceeds ``critpath_straggler_frac``
          for ``critpath_consecutive`` steps, naming ``slowest_host`` so
          the strike ledger accumulates against the lagging host."""
        raised: list[Anomaly] = []
        cfg = self.config
        cp_cooldown = (cfg.cooldown if cfg.critpath_cooldown is None
                       else cfg.critpath_cooldown)
        with self._lock:
            cp = self._critpath
            alpha = cfg.step_alpha
            for c, f in fractions.items():
                try:
                    f = float(f)
                except (TypeError, ValueError):
                    continue
                prev = cp["ewma"].get(c)
                cp["ewma"][c] = f if prev is None else prev + alpha * (f - prev)
            cp["steps"] += 1
            if cp["steps"] >= cfg.critpath_min_steps and cp["ewma"]:
                window = [round(cp["ewma"][c], 4) for c in sorted(cp["ewma"])]
                dom = max(cp["ewma"], key=lambda c: cp["ewma"][c])
                if cp["dom"] is None:
                    cp["dom"] = dom
                elif dom != cp["dom"]:
                    if cp["dom_quiet"] > 0:
                        cp["dom_quiet"] -= 1
                    else:
                        cp["dom_hits"] += 1
                        if cp["dom_hits"] >= cfg.critpath_consecutive:
                            raised.append(self._anomaly(
                                "bottleneck_shift", "critpath_dominant",
                                {"value": cp["ewma"][dom],
                                 "baseline": cp["ewma"].get(cp["dom"], 0.0),
                                 "window": window},
                                fn=f"{cp['dom']}->{dom}",
                            ))
                            cp["dom"] = dom
                            cp["dom_hits"] = 0
                            cp["dom_quiet"] = cp_cooldown
                else:
                    cp["dom_hits"] = 0
                try:
                    strag = float(fractions.get("straggler_wait") or 0.0)
                except (TypeError, ValueError):
                    strag = 0.0
                if strag <= cfg.critpath_straggler_frac:
                    cp["strag_hits"] = 0
                elif cp["strag_quiet"] > 0:
                    cp["strag_quiet"] -= 1
                else:
                    cp["strag_hits"] += 1
                    if cp["strag_hits"] >= cfg.critpath_consecutive:
                        cp["strag_hits"] = 0
                        cp["strag_quiet"] = cp_cooldown
                        raised.append(self._anomaly(
                            "bottleneck_shift", "critpath_straggler_band",
                            {"value": strag,
                             "baseline": cfg.critpath_straggler_frac,
                             "window": window},
                            suspect_host=slowest_host,
                        ))
        for a in raised:
            self._publish(a)

    def _on_recompile(self) -> list:
        hit = self._recompiles.tick()
        if not hit:
            return []
        return [self._anomaly("recompile_storm", "rate", hit)]

    def _anomaly(self, kind: str, detector: str, hit: dict, *,
                 fn: Optional[str] = None,
                 suspect_host: Optional[Any] = None) -> Anomaly:
        cfg = self.config
        value = float(hit.get("value") or 0.0)
        baseline = float(hit.get("baseline") or 0.0)
        severity = "warn"
        if baseline > 0 and value / baseline >= cfg.critical_factor:
            severity = "critical"
        if suspect_host is None and kind in ("step_time_drift", "goodput_drop"):
            from thunder_tpu.observability.events import host_identity

            suspect_host = host_identity()["host"]
        return Anomaly(
            kind=kind, severity=severity, value=value, baseline=baseline,
            ts=time.time(), detector=detector,
            window=list(hit.get("window") or ()),
            suspect_host=suspect_host, fn=fn,
        )

    # -- publication (outside the lock) ----------------------------------------

    def _publish(self, a: Anomaly) -> None:
        self.anomalies.append(a)
        try:
            from thunder_tpu.observability import events as obs_events
            from thunder_tpu.observability import metrics as obsm

            if obsm.enabled():
                obsm.ANOMALIES.inc(kind=a.kind)
            obs_events.emit_event("anomaly", **a.as_event_fields())
        except Exception:
            pass
        try:
            from thunder_tpu.resilience import autopilot as ap_mod

            ap = ap_mod.current()
            if ap is not None:
                ap.note_anomaly({
                    "anomaly": a.kind, "severity": a.severity, "ts": a.ts,
                    "value": a.value, "baseline": a.baseline,
                    "suspect_host": a.suspect_host,
                })
        except Exception:
            pass

    # -- introspection ---------------------------------------------------------

    def recent_anomalies(self, *, within_s: Optional[float] = None) -> list:
        now = time.time()
        return [
            a for a in list(self.anomalies)
            if within_s is None or now - a.ts <= within_s
        ]

    def spread_state(self) -> Optional[dict]:
        """Online fleet-spread snapshot (None until ≥2 hosts reported) —
        the /healthz host-health component when no offline summary ran."""
        with self._lock:
            if len(self._spread_acc) < 2:
                return None
            median, spread = self._spread_acc.spread()
            stats = self._spread_acc.host_stats()
        return {
            "spread_ratio": round(spread, 4),
            "hosts": len(stats),
            "stragglers": [
                h for h, st in sorted(stats.items(), key=lambda kv: str(kv[0]))
                if median and st["mean_s"] > self.config.spread_threshold * median
            ],
        }

    def slice_spread_state(self) -> Optional[dict]:
        """Online DCN-tier spread snapshot (None until ≥2 slices reported)
        — the /healthz federation component's slow-slice flag (ISSUE 18)."""
        with self._lock:
            if len(self._slice_acc) < 2:
                return None
            median, spread = self._slice_acc.spread()
            stats = self._slice_acc.host_stats()
        return {
            "spread_ratio": round(spread, 4),
            "slices": len(stats),
            "slow_slices": [
                sl for sl, st in sorted(stats.items(), key=lambda kv: str(kv[0]))
                if median
                and st["mean_s"] > self.config.slice_spread_threshold * median
            ],
        }

    def debug_state(self) -> dict:
        with self._lock:
            return {
                "consumed": self.consumed,
                "step_streams": sorted(self._step),
                "roofline_streams": len(self._roofline),
                "slices": len(self._slice_acc),
                "critpath_steps": self._critpath["steps"],
                "critpath_dominant": self._critpath["dom"],
                "recompile_window": len(self._recompiles._ticks),
                "anomalies": [
                    dict(a.as_event_fields(), ts=round(a.ts, 3))
                    for a in list(self.anomalies)[-16:]
                ],
            }
