"""Continuous roofline ledger: duty-cycled in-loop profiling (ISSUE 19).

The attribution pipeline (``profile`` → ``attribute`` →
``join_cost_attribution``) is accurate but manual: someone has to run it,
read the table, and remember what it said last week. This module makes it
continuous. A :class:`RooflineSampler` rides the training loop and, every N
steps (``THUNDER_TPU_ROOFLINE_EVERY``, off by default), runs ONE step under
the existing :func:`~thunder_tpu.observability.profile.profile` bracket,
joins the measured per-op device time against the static cost model, and
folds the result into a bounded in-memory :class:`RooflineLedger`:

    op scope -> measured us/step, flops, bytes, roofline ceiling
    (``max(flops/peak, bytes/hbm_bw, comm/ici_bw)`` from analysis/cost),
    achieved-fraction, bound-class, and a trend over recent probes.

Every probe also streams each op's measured/predicted ratio into the ops
plane's :class:`~thunder_tpu.observability.detect.DetectorBank`
(``note_roofline_op``), so a mispriced cost model raises a typed
``cost_model_drift`` anomaly — and a regressed executor-claimed kernel a
``kernel_regression`` — in-run, not at the next manual profile. The live
ledger is served at ``/debug/roofline`` and printable via
``thunder_tpu.monitor.roofline_report()``; ``bench.py`` commits it as the
``ROOFLINE_r*.json`` per-op series that ``scripts/perf_report.py --gate``
enforces. docs/performance.md ("continuous roofline ledger") walks the
workflow.

Off-path cost: when no probe is due, :meth:`RooflineSampler.maybe_sample`
is one counter bump and a modulo — ``scripts/lint_traces.py --roofline``
gates it below 1% of a gpt-tiny CPU step. With ``every=0`` (the default)
no probe ever runs.
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

ENV_EVERY = "THUNDER_TPU_ROOFLINE_EVERY"

# The committed-artifact row schema: every ledger row (and every row of a
# ROOFLINE_r*.json round) carries exactly these fields. lint_traces
# --roofline and tests/test_roofline.py validate against this tuple.
ROW_FIELDS = (
    "label", "sym", "line", "measured_us", "flops", "bytes",
    "roofline_us", "achieved_frac", "bound", "share", "executor",
    "samples", "trend",
)

# |mean(newer half) - mean(older half)| of the achieved-fraction history
# below this is "flat" — achieved fractions live in [0, 1] so an absolute
# band beats a relative one near zero.
TREND_EPS = 0.05


@dataclass
class RooflineEntry:
    """One op scope's ledger row: the latest probe's measurement joined
    with its static bound, plus a bounded achieved-fraction history that
    classifies the trend across probes."""

    label: str
    sym: str
    line: int
    pass_name: Optional[str] = None
    measured_us: float = 0.0  # latest probe, per step
    share: float = 0.0  # of device-busy time, latest probe
    flops: Optional[float] = None
    bytes: Optional[float] = None
    roofline_us: Optional[float] = None  # static ceiling
    achieved_frac: Optional[float] = None  # roofline/measured, capped at 1
    bound: Optional[str] = None  # compute|memory|comm|free
    executor: Optional[str] = None  # claiming executor, None = inline jax
    samples: int = 0  # probes that saw this op
    last_ts: float = 0.0
    history: deque = field(
        default_factory=lambda: deque(maxlen=32), repr=False)

    @property
    def trend(self) -> str:
        """``improving`` / ``degrading`` / ``flat`` over the achieved-
        fraction history (newer-half mean vs older-half mean)."""
        h = [v for v in self.history if v is not None]
        if len(h) < 4:
            return "flat"
        half = len(h) // 2
        old = sum(h[:half]) / half
        new = sum(h[half:]) / (len(h) - half)
        if new - old > TREND_EPS:
            return "improving"
        if old - new > TREND_EPS:
            return "degrading"
        return "flat"

    def as_row(self) -> dict:
        """JSON-safe row in the committed ``ROW_FIELDS`` schema."""
        return {
            "label": self.label,
            "sym": self.sym,
            "line": self.line,
            "measured_us": round(self.measured_us, 3),
            "flops": self.flops,
            "bytes": self.bytes,
            "roofline_us": (
                round(self.roofline_us, 3)
                if self.roofline_us is not None else None),
            "achieved_frac": (
                round(self.achieved_frac, 4)
                if self.achieved_frac is not None else None),
            "bound": self.bound,
            "share": round(self.share, 4),
            "executor": self.executor,
            "samples": self.samples,
            "trend": self.trend,
        }


class RooflineLedger:
    """Bounded per-op ledger folded from probe joins.

    Keyed by scope label; at most ``max_ops`` entries — on overflow the
    cheapest op (smallest measured time) is evicted, since the ledger
    exists to watch the ops that own the step. Thread-compatible with the
    sampler's single-probe-at-a-time discipline; reads
    (:meth:`snapshot` / :meth:`rows`) copy under no lock because folds
    replace scalar fields atomically."""

    def __init__(self, *, max_ops: int = 256, history: int = 32,
                 clock: Callable[[], float] = time.time):
        self.max_ops = int(max_ops)
        self.history = int(history)
        self._clock = clock
        self._entries: dict[str, RooflineEntry] = {}
        self.folds = 0

    def __len__(self) -> int:
        return len(self._entries)

    def fold(self, join: Any, *,
             executor_by_sym: Optional[dict] = None) -> list[RooflineEntry]:
        """Fold one :class:`~thunder_tpu.observability.attribution.PerfJoin`
        (one probe) into the ledger; returns the entries it touched."""
        now = self._clock()
        touched: list[RooflineEntry] = []
        for row in join.rows:
            e = self._entries.get(row.label)
            if e is None:
                e = self._entries[row.label] = RooflineEntry(
                    label=row.label, sym=row.sym, line=row.line,
                    pass_name=row.pass_name,
                    history=deque(maxlen=self.history),
                )
            e.measured_us = float(row.measured_us)
            e.share = float(row.share)
            e.flops = row.flops
            e.bytes = getattr(row, "bytes_moved", None)
            e.roofline_us = row.roofline_us
            e.achieved_frac = row.efficiency
            e.bound = row.bound
            if executor_by_sym:
                e.executor = executor_by_sym.get(row.sym, e.executor)
            e.samples += 1
            e.last_ts = now
            e.history.append(row.efficiency)
            touched.append(e)
        while len(self._entries) > self.max_ops:
            cheapest = min(self._entries.values(), key=lambda x: x.measured_us)
            del self._entries[cheapest.label]
        self.folds += 1
        return touched

    def rows(self) -> list[RooflineEntry]:
        return sorted(self._entries.values(), key=lambda e: -e.measured_us)

    def snapshot(self) -> dict:
        """JSON-safe state for ``/debug/roofline`` and the bench artifact."""
        return {
            "folds": self.folds,
            "ops": len(self._entries),
            "schema": list(ROW_FIELDS),
            "rows": [e.as_row() for e in self.rows()],
        }

    def format(self, top_k: int = 10) -> str:
        lines = [
            f"roofline ledger: {len(self._entries)} op(s), "
            f"{self.folds} probe(s) folded",
            f"  {'op':<34} {'us/step':>9} {'achieved':>9} {'bound':>8} "
            f"{'trend':>10} {'n':>3}",
        ]
        for e in self.rows()[:top_k]:
            ach = (f"{e.achieved_frac * 100:.0f}%"
                   if e.achieved_frac is not None else "-")
            lines.append(
                f"  {e.label:<34.34} {e.measured_us:>9.1f} {ach:>9} "
                f"{e.bound or '-':>8} {e.trend:>10} {e.samples:>3}"
            )
        return "\n".join(lines)


class RooflineSampler:
    """Duty-cycled in-loop profiler feeding the ledger and the detectors.

    Wrap the step::

        sampler = monitor.roofline(jfn, every=200)
        for batch in data:
            loss = sampler.maybe_sample(jfn, params, batch)

    Every ``every``-th call runs under the profile bracket (one step, no
    warmup), attributes the trace back to scopes (annotated codegen +
    the compiled HLO text recovered from the jit cache entry), joins with
    ``trace_cost`` of the execution trace, folds into the ledger, and
    streams each op's measured/predicted ratio into the ops-plane
    :class:`~thunder_tpu.observability.detect.DetectorBank`. All other
    calls pay one counter bump. ``every <= 0`` (the default when
    ``THUNDER_TPU_ROOFLINE_EVERY`` is unset) never probes."""

    def __init__(self, jfn: Any = None, *, every: Optional[int] = None,
                 device: Any = None, hlo_text: Optional[str] = None,
                 ledger: Optional[RooflineLedger] = None,
                 bank: Any = None, step_name: str = "roofline_probe"):
        if every is None:
            try:
                every = int(os.environ.get(ENV_EVERY, "0") or 0)
            except ValueError:
                every = 0
        self.every = max(0, int(every))
        self.jfn = jfn
        self.device = device
        self.step_name = step_name
        self.ledger = ledger if ledger is not None else RooflineLedger()
        self._bank = bank
        self._hlo_text = hlo_text
        self._cost: Any = None
        self._executor_by_sym: Optional[dict] = None
        self._resolved = False
        self._step = 0
        self.probes = 0
        self.last_coverage: Optional[float] = None  # of the last probe's join

    # -- duty cycle ------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.every > 0

    def tick(self) -> bool:
        """Advance the duty cycle; True when the next step is a probe.
        This bump-and-modulo is the entire per-step cost when sampling is
        armed but no probe is due (gated < 1% of a step by
        ``lint_traces --roofline``)."""
        if self.every <= 0:
            return False
        self._step += 1
        return self._step % self.every == 0

    def maybe_sample(self, fn: Callable, *args, **kwargs) -> Any:
        """Call in place of ``fn(*args, **kwargs)``; returns ``fn``'s
        output either way. Probes when the duty cycle says so."""
        if not self.tick():
            return fn(*args, **kwargs)
        return self.sample(fn, *args, **kwargs)

    # -- the probe -------------------------------------------------------------

    def _resolve(self, jfn: Any) -> None:
        """One-shot: recover the static half of the join from the jit
        compile stats — the execution trace prices via ``trace_cost``, the
        cache entry's computation lowers to the HLO text that maps raw op
        names back to scopes (required on backends whose trace events
        carry no scoped metadata, e.g. CPU), and the bound symbols name
        which executor claimed each sym."""
        if self._resolved:
            return
        self._resolved = True
        cs = getattr(jfn, "_lc_cs", None)
        if cs is None:
            log.warning(
                "roofline: %r has no compile stats (_lc_cs); probing "
                "without the static cost model — no ceilings, no drift "
                "detection", jfn)
            return
        try:
            if self._cost is None:
                from thunder_tpu.analysis.cost import trace_cost

                trace = cs.last_traces[-1]
                self._cost = trace_cost(trace, self.device)
                self._executor_by_sym = {
                    b.sym.name: b.sym.executor.name
                    for b in trace.bound_symbols
                    if getattr(b.sym, "executor", None) is not None
                }
            if self._hlo_text is None:
                entry = cs.cache_entries[-1]
                self._hlo_text = (
                    entry.computation_fn
                    .lower(*entry.hlo_audit_avals)
                    .compile().as_text())
        except Exception as e:
            log.warning("roofline: static-join setup failed (%s: %s); "
                        "continuing with what resolved", type(e).__name__, e)

    def sample(self, fn: Callable, *args, **kwargs) -> Any:
        """Run one probed step now (ignores the duty cycle): profile →
        attribute → join → fold → feed detectors. Returns ``fn``'s
        output; a failed join never fails the step."""
        from thunder_tpu.observability.profile import profile as profile_bracket

        self._resolve(self.jfn if self.jfn is not None else fn)
        box: dict[str, Any] = {}

        def _probe_step():
            box["out"] = fn(*args, **kwargs)
            return box["out"]

        trace_dir = tempfile.mkdtemp(prefix="thunder_tpu_roofline_")
        t0 = time.perf_counter()
        try:
            res = profile_bracket(
                _probe_step, trace_dir=trace_dir, steps=1, warmup=0,
                step_name=self.step_name)
            self.probes += 1
            try:
                from thunder_tpu.observability import metrics as obsm

                obsm.ROOFLINE_PROBES.inc_always()
            except Exception:
                pass
            touched: list[RooflineEntry] = []
            if res.get("profiler"):
                try:
                    join = self._join(trace_dir)
                    if join is not None:
                        self.last_coverage = join.attribution.coverage
                        touched = self.ledger.fold(
                            join, executor_by_sym=self._executor_by_sym)
                        self._feed_bank(touched)
                except Exception as e:
                    log.warning("roofline: probe join failed (%s: %s)",
                                type(e).__name__, e)
            try:
                from thunder_tpu.observability.events import emit_event

                emit_event(
                    "roofline_probe", step=self._step, ops=len(touched),
                    probe_s=round(time.perf_counter() - t0, 6))
            except Exception:
                pass
        finally:
            shutil.rmtree(trace_dir, ignore_errors=True)
        return box.get("out")

    def _join(self, trace_dir: str) -> Any:
        from thunder_tpu.observability.attribution import (
            attribute, join_cost_attribution)

        attr = attribute(trace_dir, hlo_text=self._hlo_text)
        if not attr.by_line:
            return None
        return join_cost_attribution(attr, self._cost, steps=1)

    def _feed_bank(self, touched: list[RooflineEntry]) -> None:
        bank = self._bank
        if bank is None:
            try:
                from thunder_tpu.observability import opsplane

                plane = opsplane.current()
                bank = plane.bank if plane is not None else None
            except Exception:
                bank = None
        if bank is None:
            return
        for e in touched:
            if e.roofline_us and e.measured_us:
                bank.note_roofline_op(
                    e.label, e.measured_us, e.roofline_us,
                    executor=e.executor)

    # -- introspection ---------------------------------------------------------

    def debug_state(self) -> dict:
        return {
            "enabled": self.enabled,
            "every": self.every,
            "steps": self._step,
            "probes": self.probes,
            "ledger": self.ledger.snapshot(),
        }


# =============================================================================
# Module singleton (the monitor-facade / ops-plane hookup)
# =============================================================================

_state: dict[str, Optional[RooflineSampler]] = {"sampler": None}


def current() -> Optional[RooflineSampler]:
    return _state["sampler"]


def enable(jfn: Any = None, *, every: Optional[int] = None,
           **kwargs) -> RooflineSampler:
    """Install (and return) the process-wide sampler —
    ``thunder_tpu.monitor.roofline(...)`` forwards here. ``every=None``
    reads ``THUNDER_TPU_ROOFLINE_EVERY`` (unset/0 = armed object, no
    probes)."""
    sampler = RooflineSampler(jfn, every=every, **kwargs)
    _state["sampler"] = sampler
    return sampler


def disable() -> None:
    _state["sampler"] = None


def debug_state() -> dict:
    """``/debug/roofline`` payload (also a key of ``/debug/state``)."""
    s = current()
    if s is None:
        return {"enabled": False}
    return s.debug_state()
