"""Live ops plane: per-host HTTP endpoints + the flight recorder (ISSUE 15).

The runtime half of "can you operate this fleet": a process you can SCRAPE
(``/metrics``), ASK (``/healthz``, ``/debug/state``), and whose faults
carry their own preceding context (the flight recorder's black-box dump) —
the online mirror of the offline log-merge/replay/bench-gate pipeline.

Off by default; the entire plane arms via :func:`enable` (facade:
``thunder_tpu.monitor.serve()``) or ``THUNDER_TPU_OPS_PORT``. With it off
nothing is installed: the event emit paths pay ONE module-global truth
test and the dispatch fast path pays nothing at all.

**Flight recorder** — a bounded in-memory ring of the last N structured
events (everything the event pipeline emits, step timings included), kept
even when ``THUNDER_TPU_EVENTS`` is unset. On a fault that matters —
``CollectiveTimeoutError``, ``SDCDetectedError``, ``AutopilotHalt``, an
unhandled dispatch fault — the ring atomically dumps a self-contained
``flightrec-<ts>-<reason>.jsonl`` (tmp-write → rename, bounded retention)
whose records validate against the event schema and whose trailing
``flightrec_dump`` marker tells the replay correlation rules "this log is
a fault-in-progress capture" (recoveries pending at dump time are not
failures of the run, they are the reason the dump exists). ``/debug/
flightrec`` dumps on demand.

**Ops server** — a stdlib ``ThreadingHTTPServer`` on a daemon thread:

==================  =========================================================
``/metrics``        ``monitor.prometheus_text(include_host=True)``
``/healthz``        typed verdict (:func:`health_verdict`): watchdog
                    arm-state + abandoned workers, last host-health spread,
                    de-opt levels, event-log drop counter, in-flight
                    snapshot flushes, quarantine registry, recent anomalies
``/debug/state``    cache_info across live jitted functions, quarantine
                    registry, autopilot strike ladders + last decisions,
                    detector + recorder state
``/debug/flightrec``  dump the ring now; returns the path + record count
==================  =========================================================

**Detectors** — :class:`~thunder_tpu.observability.detect.DetectorBank`
rides the same event tap; see that module.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from thunder_tpu.observability import events as obs_events
from thunder_tpu.observability import metrics as obsm
from thunder_tpu.observability.detect import DetectorBank, DetectorConfig

DUMP_PREFIX = "flightrec-"
_DUMP_REASONS = ("collective_timeout", "sdc", "autopilot_halt",
                 "dispatch_fault", "manual")


# =============================================================================
# Flight recorder
# =============================================================================


class FlightRecorder:
    """Bounded ring of fully-enveloped event records + atomic fault dumps.

    ``record`` is the ops-plane event tap: it builds the same envelope the
    JSONL log writes (``v``/``ts``/``seq``/``kind``/``pid``/``host`` — its
    own monotonic ``seq``) so a dumped file replays through
    ``analysis/events.replay_events`` unmodified. ``dump`` snapshots the
    ring, writes ``<dir>/flightrec-<ts>-<reason>.jsonl`` via tmp→rename
    (a crash mid-dump can never tear a dump), appends the
    ``flightrec_dump`` trailer marker, sweeps retention down to ``keep``
    files, and records the dump. Dumps with NO new records since the last
    one are skipped (``reason="manual"`` excepted): one fault unwinding
    through several except blocks must not spray identical dumps."""

    def __init__(self, capacity: int = 512, directory: Optional[str] = None,
                 keep: int = 16):
        self.capacity = int(capacity)
        self.keep = int(keep)
        self.directory = directory or os.environ.get(
            "THUNDER_TPU_FLIGHTREC_DIR", ""
        ) or os.path.join(os.getcwd(), "flightrec")
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._last_dump_seq = -1
        self._lock = threading.Lock()
        self.dumps: deque = deque(maxlen=32)  # (ts, reason, path, n_records)
        self._dead = False

    # -- the tap ---------------------------------------------------------------

    def record(self, kind: str, fields: dict) -> None:
        rec = {"v": obs_events.SCHEMA_VERSION, "ts": time.time(), "kind": kind}
        rec.update(obs_events.host_identity())
        rec.update(fields)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._ring.append(rec)

    def __len__(self) -> int:
        return len(self._ring)

    def snapshot(self) -> list:
        with self._lock:
            return [dict(r) for r in self._ring]

    # -- dumping ---------------------------------------------------------------

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Atomically dump the ring; returns the path, or None when skipped
        (no new records since the last dump, a dead directory, or I/O
        failure — the black box must never take the workload down)."""
        if self._dead:
            return None
        with self._lock:
            if self._seq == self._last_dump_seq and reason != "manual":
                return None  # same fault unwinding through a second trigger
            records = [dict(r) for r in self._ring]
            self._last_dump_seq = self._seq
            trailer_seq = self._seq
        now = time.time()
        trailer = {
            "v": obs_events.SCHEMA_VERSION, "ts": now,
            "kind": "flightrec_dump", "reason": str(reason),
            "records": len(records), "seq": trailer_seq,
        }
        trailer.update(obs_events.host_identity())
        stamp = time.strftime("%Y%m%dT%H%M%S", time.localtime(now))
        name = f"{DUMP_PREFIX}{stamp}.{int(now * 1e3) % 1000:03d}-{reason}.jsonl"
        path = os.path.join(self.directory, name)
        n = 1
        while os.path.exists(path):
            path = os.path.join(self.directory, f"{name[:-6]}.{n}.jsonl")
            n += 1
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                for rec in records:
                    f.write(json.dumps(rec, default=str))
                    f.write("\n")
                f.write(json.dumps(trailer, default=str))
                f.write("\n")
            os.replace(tmp, path)
        except OSError as e:
            self._dead = True
            import warnings

            warnings.warn(
                f"thunder_tpu flight recorder disabled after I/O failure "
                f"under {self.directory!r}: {e}", stacklevel=2,
            )
            return None
        obsm.FLIGHTREC_DUMPS.inc_always(reason=str(reason))
        self.dumps.append((now, str(reason), path, len(records)))
        self._sweep()
        return path

    def _sweep(self) -> None:
        try:
            names = sorted(
                n for n in os.listdir(self.directory)
                if n.startswith(DUMP_PREFIX) and n.endswith(".jsonl")
            )
        except OSError:
            return
        for name in names[:-self.keep] if self.keep > 0 else []:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:
                pass

    def debug_state(self) -> dict:
        return {
            "capacity": self.capacity,
            "records": len(self._ring),
            "directory": self.directory,
            "dumps": [
                {"ts": round(ts, 3), "reason": reason, "path": path,
                 "records": n}
                for ts, reason, path, n in list(self.dumps)
            ],
        }


# =============================================================================
# Health verdict
# =============================================================================

_STATUS_RANK = {"ok": 0, "degraded": 1, "critical": 2}


def _worst(a: str, b: str) -> str:
    return a if _STATUS_RANK[a] >= _STATUS_RANK[b] else b


def health_verdict(plane: Optional["OpsPlane"] = None, *,
                   spread_threshold: float = 1.5,
                   flush_stuck_s: float = 30.0,
                   anomaly_window_s: float = 300.0) -> dict:
    """The typed ``/healthz`` verdict: per-component status composed into
    the worst overall. Components (docs/observability.md "ops plane"):

    - ``event_log`` — the ``thunder_tpu_event_log_dropped_total`` counter
      (``inc_always``: visible with metrics off); any dropped sink means
      this host is flying blind → degraded;
    - ``watchdog`` — armed state + abandoned workers (degraded when any
      worker leaked, critical at the refuse-to-arm cap);
    - ``host_health`` — the detector bank's ONLINE spread when ≥2 hosts
      reported, else the last offline ``host_health`` summary; stragglers
      → degraded;
    - ``federation`` — per-slice fleet health when a membership ledger is
      installed (ISSUE 18): lost/cooldown slices or a DCN-tier slow slice
      → degraded, zero surviving width → critical; absent on unfederated
      runs;
    - ``deopt`` — the process-wide max de-opt ladder level (any de-opted
      function → degraded: the process is trading speed for survival);
    - ``checkpoint`` — in-flight background flushes; one stuck past
      ``flush_stuck_s`` → degraded (disk durability is stalling);
    - ``quarantine`` — live executor quarantines → degraded;
    - ``profile`` — profiler bracket captures (ISSUE 19): any capture
      that degraded to wall clock (missing plugin) → degraded, since
      every roofline duty-cycle probe behind it measured nothing;
    - ``timeline`` — the fleet critical-path recorder (ISSUE 20) when
      armed: fewer than two reporting hosts (nothing to cross-host join)
      or low clock-alignment confidence → degraded; absent when no
      recorder is installed;
    - ``anomalies`` — detector verdicts within ``anomaly_window_s``:
      any warn → degraded, any critical → critical."""
    plane = plane if plane is not None else current()
    status = "ok"
    reasons: list[str] = []
    components: dict[str, Any] = {}

    def comp(name: str, st: str, detail: dict, reason: Optional[str] = None):
        nonlocal status
        components[name] = dict(detail, status=st)
        if st != "ok" and reason:
            reasons.append(reason)
        status = _worst(status, st)

    dropped = obsm.EVENT_LOG_DROPPED.value()
    comp("event_log", "degraded" if dropped else "ok",
         {"dropped_sinks": dropped},
         f"{dropped} event-log sink(s) lost to I/O failure")

    from thunder_tpu.resilience import watchdog as wd

    abandoned = wd.abandoned_worker_count()
    cap = wd.max_abandoned_workers()
    wd_status = "ok"
    if abandoned >= cap:
        wd_status = "critical"
    elif abandoned:
        wd_status = "degraded"
    comp("watchdog", wd_status,
         {"armed": wd.enabled(), "timeout_s": wd.active_timeout(),
          "abandoned_workers": abandoned, "cap": cap},
         f"{abandoned}/{cap} abandoned watchdog worker(s)")

    spread = None
    stragglers: list = []
    if plane is not None and plane.bank is not None:
        online = plane.bank.spread_state()
        if online is not None:
            spread = online["spread_ratio"]
            stragglers = online["stragglers"]
    if spread is None:
        summary = wd.last_host_health()
        if summary:
            spread = summary.get("spread_ratio")
            stragglers = list(summary.get("stragglers") or ())
    hh_status = "degraded" if stragglers else "ok"
    comp("host_health", hh_status,
         {"spread_ratio": spread, "stragglers": stragglers},
         f"straggler suspect(s): {stragglers}")

    from thunder_tpu.resilience import federation as fed_mod

    ledger = fed_mod.current_ledger()
    if ledger is not None:
        fed = ledger.debug_state()
        lost = [r["slice"] for r in fed["slices"] if r["state"] == "lost"]
        cooldown = [r["slice"] for r in fed["slices"]
                    if r["state"] == "cooldown"]
        slow = None
        if plane is not None and plane.bank is not None:
            ss = plane.bank.slice_spread_state()
            if ss is not None:
                slow = ss["slow_slices"]
        fed_status = "ok"
        if cooldown or slow:
            fed_status = "degraded"
        if lost:
            fed_status = "degraded" if fed["width"] else "critical"
        comp("federation", fed_status,
             {"width": fed["width"], "n_slices": fed["n_slices"],
              "lost_slices": lost, "cooldown_slices": cooldown,
              "slow_slices": slow},
             f"fleet at width {fed['width']}/{fed['n_slices']} "
             f"(lost={lost}, cooldown={cooldown}, slow={slow})")

    from thunder_tpu.resilience import deopt as deopt_mod

    level = deopt_mod.process_max_level()
    comp("deopt", "degraded" if level else "ok", {"max_level": level},
         f"de-opt ladder at L{level} (speed traded for survival)")

    from thunder_tpu.resilience import preemption as preempt_mod

    flushes = preempt_mod.inflight_flushes()
    stuck = [f for f in flushes if f["for_s"] > flush_stuck_s]
    comp("checkpoint", "degraded" if stuck else "ok",
         {"inflight_flushes": flushes},
         f"background flush stuck > {flush_stuck_s:g}s: {stuck}")

    from thunder_tpu.resilience import demotion

    quarantined = demotion.quarantine_snapshot()
    comp("quarantine", "degraded" if quarantined else "ok",
         {"entries": len(quarantined)},
         f"{len(quarantined)} quarantined (sym, executor) pair(s)")

    # Degraded profiler captures (ISSUE 19): any ok="false" bump means a
    # profile bracket ran without the plugin — wall-clock-only duty cycles
    # would otherwise stay invisible until someone read the ledger and
    # noticed it never grew.
    degraded_caps = obsm.PROFILE_CAPTURES.value(ok="false")
    comp("profile", "degraded" if degraded_caps else "ok",
         {"captures_ok": obsm.PROFILE_CAPTURES.value(ok="true"),
          "captures_degraded": degraded_caps},
         f"{degraded_caps} profiler capture(s) degraded to wall clock "
         "(no profiler plugin)")

    # Fleet timeline (ISSUE 20): a silently dead critical-path recorder
    # must be as visible as a missing profiler — degraded when fewer than
    # two hosts ever reported (no cross-host path to decompose) or when the
    # weakest non-outlier clock alignment is low-confidence.
    from thunder_tpu.observability import timeline as timeline_mod

    tl = timeline_mod.health_state()
    if tl is not None:
        conf = tl.get("min_confidence")
        tl_status = "ok"
        if tl["hosts"] < 2:
            tl_status = "degraded"
        elif conf is not None and conf < 0.5:
            tl_status = "degraded"
        comp("timeline", tl_status, tl,
             f"fleet timeline degraded: hosts={tl['hosts']}, "
             f"alignment confidence={conf}")

    recent: list = []
    if plane is not None and plane.bank is not None:
        recent = plane.bank.recent_anomalies(within_s=anomaly_window_s)
    an_status = "ok"
    for a in recent:
        an_status = _worst(an_status, "critical" if a.severity == "critical"
                           else "degraded")
    comp("anomalies", an_status,
         {"recent": [
             {"anomaly": a.kind, "severity": a.severity, "ts": round(a.ts, 3),
              "value": round(a.value, 6), "suspect_host": a.suspect_host}
             for a in recent[-8:]
         ]},
         f"{len(recent)} anomaly(ies) in the last {anomaly_window_s:g}s")

    if plane is not None and plane.recorder is not None:
        components["flight_recorder"] = {
            "status": "ok",
            "records": len(plane.recorder),
            "dumps": len(plane.recorder.dumps),
        }
    return {"status": status, "reasons": reasons, "components": components,
            "ts": round(time.time(), 3)}


def debug_state(plane: Optional["OpsPlane"] = None) -> dict:
    """The ``/debug/state`` payload: everything an operator attaches to a
    ticket — per-function cache/compile state, quarantines, the autopilot's
    hysteresis ladders and last decisions, detector + recorder state."""
    plane = plane if plane is not None else current()
    from thunder_tpu import api
    from thunder_tpu.resilience import autopilot as ap_mod
    from thunder_tpu.resilience import demotion

    out: dict[str, Any] = {
        "cache": api.live_function_state(),
        "quarantine": {
            f"{sym}|{ex}": round(ttl, 1)
            for (sym, ex), ttl in demotion.quarantine_snapshot().items()
        },
    }
    ap = ap_mod.current()
    out["autopilot"] = ap.debug_state() if ap is not None else None
    from thunder_tpu.resilience import federation as fed_mod

    ledger = fed_mod.current_ledger()
    out["federation"] = ledger.debug_state() if ledger is not None else None
    # `is not None`, not truthiness: an EMPTY FlightRecorder is falsy
    # (it defines __len__) but very much installed.
    out["flight_recorder"] = (
        plane.recorder.debug_state()
        if plane is not None and plane.recorder is not None else None
    )
    out["detectors"] = (
        plane.bank.debug_state()
        if plane is not None and plane.bank is not None else None
    )
    from thunder_tpu.observability import roofline as roofline_mod

    out["roofline"] = roofline_mod.debug_state()
    from thunder_tpu.observability import timeline as timeline_mod

    out["timeline"] = timeline_mod.debug_state()
    return out


# =============================================================================
# The HTTP server
# =============================================================================


class OpsServer:
    """stdlib-threaded HTTP endpoint serving the ops routes. Binds
    ``127.0.0.1`` by default (``THUNDER_TPU_OPS_HOST`` widens it); port 0
    asks the OS for an ephemeral port — read it back from ``.port``."""

    def __init__(self, plane: "OpsPlane", port: int = 0,
                 host: Optional[str] = None):
        import http.server

        self.plane = plane
        host = host or os.environ.get("THUNDER_TPU_OPS_HOST", "127.0.0.1")
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # never spam the training job's stderr
                pass

            def _send(self, code: int, body: str, ctype: str):
                payload = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                route = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    obsm.OPS_REQUESTS.inc(route=route)
                    if route == "/metrics":
                        import thunder_tpu.monitor as monitor

                        self._send(200, monitor.prometheus_text(include_host=True),
                                   "text/plain; version=0.0.4")
                    elif route == "/healthz":
                        verdict = health_verdict(outer.plane)
                        code = 503 if verdict["status"] == "critical" else 200
                        self._send(code, json.dumps(verdict, default=str),
                                   "application/json")
                    elif route == "/debug/state":
                        self._send(200, json.dumps(debug_state(outer.plane),
                                                   default=str),
                                   "application/json")
                    elif route == "/debug/roofline":
                        from thunder_tpu.observability import (
                            roofline as roofline_mod)

                        self._send(200, json.dumps(
                            roofline_mod.debug_state(), default=str),
                            "application/json")
                    elif route == "/debug/critpath":
                        from thunder_tpu.observability import (
                            timeline as timeline_mod)

                        self._send(200, json.dumps(
                            timeline_mod.debug_state(), default=str),
                            "application/json")
                    elif route == "/debug/flightrec":
                        rec = outer.plane.recorder
                        if rec is None:
                            self._send(404, '{"error": "no flight recorder"}',
                                       "application/json")
                            return
                        path = rec.dump("manual")
                        self._send(200, json.dumps(
                            {"path": path, "records": len(rec)}),
                            "application/json")
                    else:
                        self._send(404, '{"error": "unknown route"}',
                                   "application/json")
                except BrokenPipeError:
                    pass
                except Exception as e:  # the ops plane never kills the job
                    try:
                        self._send(500, json.dumps({"error": str(e)}),
                                   "application/json")
                    except Exception:
                        pass

        self._httpd = http.server.ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="thunder-tpu-ops",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


# =============================================================================
# Plane lifecycle
# =============================================================================


class OpsPlane:
    """One enabled ops plane: recorder + detector bank + (optional) server."""

    def __init__(self, recorder: Optional[FlightRecorder],
                 bank: Optional[DetectorBank],
                 server: Optional[OpsServer] = None):
        self.recorder = recorder
        self.bank = bank
        self.server = server

    @property
    def port(self) -> Optional[int]:
        return self.server.port if self.server is not None else None

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
            self.server = None


_state: dict = {"plane": None, "autostarted": False}


def current() -> Optional[OpsPlane]:
    return _state["plane"]


def enable(port: Optional[int] = None, *,
           serve: Optional[bool] = None,
           flightrec: bool = True,
           flightrec_capacity: int = 512,
           flightrec_dir: Optional[str] = None,
           flightrec_keep: int = 16,
           detectors: Any = True) -> OpsPlane:
    """Arm the ops plane (facade: ``thunder_tpu.monitor.serve()``).

    ``port`` (or ``THUNDER_TPU_OPS_PORT``; 0 = ephemeral) starts the HTTP
    server; ``serve=False`` arms only the recorder + detectors (the soak's
    headless spelling still serves — pass both explicitly). ``detectors``
    is True (defaults), a :class:`~thunder_tpu.observability.detect.
    DetectorConfig`, or False. Re-enabling replaces the previous plane.
    Returns the :class:`OpsPlane`; ``plane.port`` holds the bound port."""
    disable()
    recorder = FlightRecorder(
        capacity=flightrec_capacity, directory=flightrec_dir,
        keep=flightrec_keep,
    ) if flightrec else None
    bank = None
    if detectors:
        cfg = detectors if isinstance(detectors, DetectorConfig) else None
        bank = DetectorBank(cfg)
    plane = OpsPlane(recorder, bank)
    if serve is None:
        serve = port is not None or bool(
            os.environ.get("THUNDER_TPU_OPS_PORT", "").strip())
    if serve:
        if port is None:
            try:
                port = int(os.environ.get("THUNDER_TPU_OPS_PORT", "0"))
            except ValueError:
                port = 0
        # Bind BEFORE installing the event taps: a failed bind must leave
        # nothing armed (taps with no registered plane would silently tax
        # every emit and write dumps nobody can find or shut down).
        plane.server = OpsServer(plane, port=port)
    taps = []
    if recorder is not None:
        taps.append(recorder.record)
    if bank is not None:
        taps.append(bank.consume)
    obs_events.set_ops_taps(tuple(taps), recorder=recorder)
    _state["plane"] = plane
    return plane


def disable() -> None:
    """Tear the plane down: stop the server, uninstall the event taps."""
    plane = _state["plane"]
    _state["plane"] = None
    obs_events.set_ops_taps((), recorder=None)
    if plane is not None:
        plane.close()


def maybe_autostart() -> Optional[OpsPlane]:
    """One-shot env autostart (``api._ensure_runtime`` calls this when
    ``THUNDER_TPU_OPS_PORT`` is set): the zero-config spelling for a fleet
    launched by a scheduler that exports one port per process."""
    if _state["autostarted"] or _state["plane"] is not None:
        return _state["plane"]
    _state["autostarted"] = True
    env = os.environ.get("THUNDER_TPU_OPS_PORT", "").strip()
    if not env:
        return None
    try:
        port = int(env)
    except ValueError:
        return None
    try:
        return enable(port=port)
    except OSError:
        import warnings

        warnings.warn(
            f"thunder_tpu ops plane: cannot bind THUNDER_TPU_OPS_PORT={env}",
            stacklevel=2,
        )
        return None


def flight_dump(reason: str = "manual") -> Optional[str]:
    """Dump the flight recorder now (no-op None when the plane is off) —
    delegates to the one installed-recorder source of truth the fault
    sites use (``events.flight_dump``)."""
    return obs_events.flight_dump(reason)
