"""The executor framework: pluggable backends claiming trace symbols.

Reference parity: thunder/extend/__init__.py (`Executor:47`,
`OperatorExecutor:190`, `FusionExecutor:132`, `ImplInfo:32`,
`register_executor:275`, default/always registries `:268-388`,
optimization fuel `:136-155`).

Executors are priority-ordered: the claiming pass
(thunder_tpu/executors/passes.py) hands each bound symbol to the first
executor whose checker accepts it, descending into subsymbols when no
executor claims a composite op. On TPU the terminal executor is the JAX/XLA
operator executor (thunder_tpu/executors/jaxex.py) — "fusion" is XLA staging
the whole claimed trace under one jit — while Pallas kernels register as
higher-priority operator executors taking the cuDNN/Triton/TE seats.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core.baseutils import check
from thunder_tpu.core.symbol import BoundSymbol, Symbol


@dataclass
class ImplInfo:
    """Reference parity: thunder/extend/__init__.py `ImplInfo:32`."""

    symbol: Optional[Symbol] = None  # executor-specific op symbol, if any
    fn: Optional[Callable] = None  # concrete implementation
    checker: Optional[Callable] = None  # (*args, **kwargs) -> bool
    execution_transform: Optional[Callable] = None  # (*args, **kwargs) -> result, records ops
    grad_transform: Optional[Callable] = None  # custom VJP rule


class Executor:
    def __init__(self, name: str, *, version: str = "0.1"):
        self.name = name
        self.version = version
        self.implmap: dict[Any, ImplInfo] = {}
        # Optimization fuel for bisecting claiming/fusion bugs
        # (reference: extend/__init__.py:136-155).
        self._fuel: Optional[int] = None

    def __repr__(self) -> str:
        return f"Executor({self.name!r})"

    # -- fuel ----------------------------------------------------------------

    def set_fuel(self, n: Optional[int]) -> None:
        self._fuel = n

    def get_fuel(self, amount: int = 1) -> bool:
        if self._fuel is None:
            return True
        if self._fuel >= amount:
            self._fuel -= amount
            return True
        return False

    # -- claiming ------------------------------------------------------------

    def can_execute(self, bsym: BoundSymbol) -> bool:
        info = self.implmap.get(bsym.sym.id)
        if info is None:
            return False
        if info.checker is not None:
            try:
                if not info.checker(*bsym.args, **bsym.kwargs):
                    return False
            except Exception:
                return False
        # When fuel is set, each claim consumes one unit; exhausting fuel
        # makes this executor stop claiming (bisection knob).
        return self.get_fuel(1)

    def get_impl(self, sym_id: Any) -> Optional[Callable]:
        info = self.implmap.get(sym_id)
        if info is None:
            return None
        if info.fn is not None:
            return info.fn
        if info.symbol is not None and info.symbol.python_impl is not None:
            return info.symbol.python_impl
        return None

    def get_execution_transform(self, sym_id: Any) -> Optional[Callable]:
        info = self.implmap.get(sym_id)
        return info.execution_transform if info is not None else None

    def get_grad_transform(self, sym_id: Any) -> Optional[Callable]:
        info = self.implmap.get(sym_id)
        return info.grad_transform if info is not None else None


class OperatorExecutor(Executor):
    """Reference parity: thunder/extend/__init__.py `OperatorExecutor:190`."""

    def register_operator(
        self,
        name: str,
        *,
        meta: Callable,
        fn: Callable,
        tags: Sequence[Any] = (),
        replaces: Optional[Any] = None,
    ) -> Symbol:
        """Create an executor-owned symbol with a concrete implementation
        (reference: `register_operator:203`)."""
        sym = Symbol(
            name,
            meta,
            id=f"{self.name}.{name}",
            is_prim=True,
            tags=tags,
            executor=self,
            python_impl=fn,
            module=self.name,
        )
        self.implmap[sym.id] = ImplInfo(symbol=sym, fn=fn)
        if replaces is not None:
            self.implmap[replaces] = ImplInfo(symbol=sym, fn=fn)
        return sym

    def register_implementation(
        self,
        sym_or_id: Symbol | Any,
        *,
        op: Optional[Symbol] = None,
        fn: Optional[Callable] = None,
        checker: Optional[Callable] = None,
        execution_transform: Optional[Callable] = None,
        grad_transform: Optional[Callable] = None,
    ) -> None:
        """Map an IR symbol to this executor (reference: `register_implementation:247`)."""
        sym_id = sym_or_id.id if isinstance(sym_or_id, Symbol) else sym_or_id
        impl_fn = fn if fn is not None else (op.python_impl if op is not None else None)
        self.implmap[sym_id] = ImplInfo(
            symbol=op,
            fn=impl_fn,
            checker=checker,
            execution_transform=execution_transform,
            grad_transform=grad_transform,
        )


class FusionExecutor(Executor):
    """An executor that rewrites whole regions (reference: `FusionExecutor:132`).

    On TPU, XLA is the fusion engine and runs below the operator executors;
    this class remains for regional executors (e.g. an explicitly-partitioned
    Pallas megakernel or a torch.compile-on-CPU region) and for API parity.
    """

    def fusion_pass(self, trace):
        raise NotImplementedError

    def register_temporary_operation(self, name: str, fn: Callable) -> Symbol:
        sym = Symbol(name, None, id=f"{self.name}.{name}", executor=self, python_impl=fn, module=self.name)
        self.implmap[sym.id] = ImplInfo(symbol=sym, fn=fn)
        return sym


# -- global registry ----------------------------------------------------------

_executor_map: dict[str, Executor] = {}
_default_executors: list[Executor] = []
_always_executors: list[Executor] = []


def register_executor(ex: Executor) -> Executor:
    _executor_map[ex.name] = ex
    return ex


def get_executor(name: str) -> Optional[Executor]:
    return _executor_map.get(name)


def get_all_executors() -> tuple[Executor, ...]:
    return tuple(_executor_map.values())


def get_default_executors() -> tuple[Executor, ...]:
    return tuple(_default_executors)


def get_always_executors() -> tuple[Executor, ...]:
    return tuple(_always_executors)


def add_default_executor(ex: Executor, *, front: bool = True) -> None:
    if ex in _default_executors:
        _default_executors.remove(ex)
    if front:
        _default_executors.insert(0, ex)
    else:
        _default_executors.append(ex)


def add_always_executor(ex: Executor) -> None:
    if ex not in _always_executors:
        _always_executors.append(ex)


def resolve_executors(executors: Optional[Sequence[Executor | str]]) -> tuple[Executor, ...]:
    if executors is None:
        return get_default_executors()
    out: list[Executor] = []
    for e in executors:
        if isinstance(e, Executor):
            out.append(e)
        else:
            ex = get_executor(e)
            check(ex is not None, lambda: f"Unknown executor {e!r}")
            out.append(ex)
    return tuple(out)


# -- lookasides ---------------------------------------------------------------

_lookasides: dict[Callable, Callable] = {}


def register_lookaside(fn: Callable, replacement: Callable) -> None:
    """Map an external callable to a traceable replacement
    (reference: extend/__init__.py `register_lookaside:391`)."""
    _lookasides[fn] = replacement


def get_lookaside(fn: Callable) -> Optional[Callable]:
    return _lookasides.get(fn)
