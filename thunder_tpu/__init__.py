"""thunder_tpu: a TPU-native source-to-source JIT compiler for PyTorch programs.

Built from scratch with the capabilities of Lightning Thunder
(reference: carmocca/lightning-thunder): programs are acquired into a
readable trace IR over a reduced primitive set, transformed (autodiff,
autocast, DCE/CSE, rematerialization, distributed rewrites), and executed by
priority-ordered pluggable executors — here JAX/XLA and Pallas kernels over
TPU, with `jax.lax` collectives on an ICI/DCN device mesh for distribution.

Public surface mirrors the reference's thunder/__init__.py: `jit`,
`last_traces`, `compile_data`, `grad`, ThunderModule, etc.
"""

__version__ = "0.1.0"

from thunder_tpu.core import dtypes, devices  # noqa: F401
from thunder_tpu import torch as _ltorch  # register the torch-mirror language  # noqa: F401
from thunder_tpu.api import (  # noqa: F401
    jit,
    grad,
    value_and_grad,
    vmap,
    jvp,
    seed,
    compile_data,
    compile_stats,
    last_traces,
    last_prologue_traces,
    last_backward_traces,
    last_compile_options,
    cache_hits,
    cache_misses,
    cache_info,
    set_execution_callback_file,
)
from thunder_tpu.common import (  # noqa: F401
    CACHE_OPTIONS,
    SHARP_EDGES_OPTIONS,
    ThunderSharpEdgeError,
    ThunderSharpEdgeWarning,
)
from thunder_tpu import monitor  # noqa: F401  # metrics facade (docs/observability.md)
from thunder_tpu import resilience  # noqa: F401  # fault injection + recovery (docs/robustness.md)
from thunder_tpu.observability.profile import profile  # noqa: F401

# Legacy entry point (reference parity: thunder.compile, thunder/__init__.py:655
# — deprecated there in favor of jit; same here). Excluded from __all__ so
# `from thunder_tpu import *` cannot shadow the Python builtin.
compile = jit

__all__ = [
    "jit", "grad", "value_and_grad", "vmap", "jvp", "seed",
    "compile_data", "compile_stats", "last_traces", "last_prologue_traces",
    "last_backward_traces", "last_compile_options", "cache_hits",
    "cache_misses", "cache_info", "set_execution_callback_file",
    "CACHE_OPTIONS", "SHARP_EDGES_OPTIONS",
    "ThunderSharpEdgeError", "ThunderSharpEdgeWarning",
    "dtypes", "devices", "monitor", "profile", "resilience",
]

