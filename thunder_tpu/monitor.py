"""``thunder_tpu.monitor`` — the operator-facing metrics facade.

One import for the serving/ops story: flip metrics on, read a snapshot,
scrape Prometheus text, or dump JSON. The heavy lifting lives in
:mod:`thunder_tpu.observability.metrics`; this module is the stable surface
(docs/observability.md lists every metric name).

    import thunder_tpu.monitor as monitor

    monitor.enable()                  # or THUNDER_TPU_METRICS=1
    ... serve traffic ...
    monitor.report()                  # nested dict snapshot
    monitor.prometheus_text()         # text exposition for a /metrics endpoint
    monitor.dump_json("metrics.json")
"""

from __future__ import annotations

from typing import Optional

from thunder_tpu.observability.metrics import (  # noqa: F401
    REGISTRY,
    MetricsRegistry,
    disable,
    enable,
    enabled,
)


def _host_labels() -> dict:
    """``{"host", "pid"}`` of this process — the writer identity the event
    log already stamps (observability/events.host_identity), reused as the
    metrics host/process dimension so logs and scrapes join on the same key."""
    from thunder_tpu.observability.events import host_identity

    ident = host_identity()
    return {"host": str(ident["host"]), "pid": str(ident["pid"])}


def report(include_host: bool = False) -> dict:
    """Full snapshot of every registered metric (histograms summarized).
    ``include_host=True`` adds the writer identity under ``"host_identity"``
    so per-host snapshots from a fleet can be aggregated unambiguously."""
    out = REGISTRY.report()
    if include_host:
        out["host_identity"] = _host_labels()
    return out


def report_compact() -> dict:
    """Flat {metric+labels: value} snapshot with empty series dropped."""
    return REGISTRY.report_compact()


def prometheus_text(include_host: bool = False) -> str:
    """Prometheus text exposition format (serve it from a /metrics route).
    ``include_host=True`` stamps ``host=``/``pid=`` labels onto every series
    (escaped per the exposition format) — the multi-host dimension that lets
    one aggregator scrape a fleet of per-process /metrics routes."""
    return REGISTRY.prometheus_text(extra_labels=_host_labels() if include_host else None)


def host_health(source, *, spread_threshold: float = 1.5):
    """Cross-host health over merged per-host event logs: per-host step-time
    stats, the fleet spread ratio (gauge
    ``thunder_tpu_host_step_time_spread_ratio``), and straggler suspects
    (``straggler_suspect`` event + warning diagnostic per flagged host).
    ``source`` is a list of per-host JSONL paths or already-merged records;
    returns ``(summary, diagnostics)``. CLI spelling:
    ``scripts/lint_traces.py --events h0.jsonl h1.jsonl ...``."""
    from thunder_tpu.analysis.events import host_health as _hh

    return _hh(source, spread_threshold=spread_threshold)


def serve(port: Optional[int] = None, **options):
    """Start the live ops plane (ISSUE 15; docs/observability.md "ops
    plane"): a per-host stdlib-threaded HTTP endpoint serving ``/metrics``
    (:func:`prometheus_text` with host labels), ``/healthz`` (the typed
    verdict), ``/debug/state``, and ``/debug/flightrec`` — plus the flight
    recorder and the streaming anomaly detectors riding the event taps.
    ``port`` 0 binds an ephemeral port (read it from the returned plane's
    ``.port``); default is ``THUNDER_TPU_OPS_PORT``. Off by default; with
    it off the hot paths pay nothing. ``options`` forward to
    ``observability.opsplane.enable`` (flightrec_dir, detectors, ...)."""
    from thunder_tpu.observability import opsplane

    options.setdefault("serve", True)
    return opsplane.enable(port=port, **options)


def ops_health() -> dict:
    """The ``/healthz`` verdict, in-process (no server needed)."""
    from thunder_tpu.observability import opsplane

    return opsplane.health_verdict()


def ops_state() -> dict:
    """The ``/debug/state`` payload, in-process."""
    from thunder_tpu.observability import opsplane

    return opsplane.debug_state()


def flight_dump(reason: str = "manual"):
    """Dump the flight recorder's ring now (None when the plane is off)."""
    from thunder_tpu.observability.events import flight_dump as _fd

    return _fd(reason)


def shutdown_ops() -> None:
    """Stop the ops server and uninstall the event taps."""
    from thunder_tpu.observability import opsplane

    opsplane.disable()


def configure_watchdog(timeout_s) -> None:
    """Arm (None disarms) the collective watchdog process-wide — the
    programmatic spelling of ``THUNDER_TPU_COLLECTIVE_TIMEOUT_S``. A
    dispatch containing collectives that exceeds the timeout raises a typed
    ``CollectiveTimeoutError`` naming the pending collective trace lines
    and the suspected host (from the last :func:`host_health` summary)
    instead of hanging forever (docs/robustness.md "distributed
    resilience")."""
    from thunder_tpu.resilience import watchdog

    watchdog.configure(timeout_s)


def last_host_health():
    """The most recent :func:`host_health` summary this process computed —
    the straggler record the collective watchdog joins its timeout errors
    against. None until ``host_health`` has run."""
    from thunder_tpu.resilience import watchdog

    return watchdog.last_host_health()


def dump_json(path: str) -> None:
    """Write the full snapshot (with a timestamp) as JSON to ``path``."""
    REGISTRY.dump_json(path)


def reset() -> None:
    """Zero every metric (definitions stay). Tests and epoch boundaries."""
    REGISTRY.reset()


def set_event_log(path: Optional[str]) -> None:
    """Point the process-wide JSONL event log at ``path`` (None disables) —
    the programmatic spelling of ``THUNDER_TPU_EVENTS``."""
    from thunder_tpu.observability.events import set_global_path

    set_global_path(path)


def attribution_report(
    trace_dir: str,
    *,
    jfn=None,
    trace=None,
    device=None,
    steps: int = 1,
    hlo_text: Optional[str] = None,
):
    """The roofline/MFU report over a profile directory: measured per-op
    device time (``observability/attribution.py``) joined with the static
    cost model (``analysis/cost.py``).

    ``trace_dir`` is a ``thunder_tpu.profile()`` output dir (profile with
    ``THUNDER_TPU_ANNOTATE_TRACES=1`` so HLO rows carry trace-line scopes).
    Pass ``jfn`` (a compiled ``thunder_tpu.jit`` function) or ``trace`` (an
    execution ``TraceCtx``) to add predicted cost, roofline ratio, and
    compute/memory-bound classification per op; ``steps`` is how many steps
    the profile bracketed (``profile()``'s ``steps=``), so measured totals
    scale to per-step numbers. Returns a ``PerfJoin``; ``print(report)`` or
    ``report.format(top_k)`` renders the table. CLI spelling:
    ``scripts/perf_report.py --trace-dir DIR``."""
    from thunder_tpu.analysis.cost import trace_cost
    from thunder_tpu.observability.attribution import attribute, join_cost_attribution

    if trace is None and jfn is not None:
        cs = getattr(jfn, "_lc_cs", None)
        if cs is not None and getattr(cs, "last_traces", None):
            trace = cs.last_traces[-1]
    cost = trace_cost(trace, device) if trace is not None else None
    attr = attribute(trace_dir, hlo_text=hlo_text)
    join = join_cost_attribution(attr, cost, steps=steps)
    return join


def roofline(jfn=None, *, every: Optional[int] = None, **options):
    """Arm the continuous roofline ledger (ISSUE 19): install a
    process-wide duty-cycled sampler that, every ``every`` steps, runs one
    step under the profiler bracket, joins measured per-op time with the
    static cost model, folds the result into the bounded per-op ledger,
    and streams measured/predicted ratios into the ops-plane drift
    detectors (``cost_model_drift`` / ``kernel_regression`` anomalies).

    Wrap the step with the returned sampler::

        sampler = monitor.roofline(jfn, every=200)
        for batch in data:
            loss = sampler.maybe_sample(jfn, params, batch)

    ``every=None`` reads ``THUNDER_TPU_ROOFLINE_EVERY`` (unset/0 = never
    probes — the off-path cost is one counter bump). Live ledger:
    ``/debug/roofline`` when the ops plane serves, or
    :func:`roofline_report`; ``options`` forward to
    ``observability.roofline.enable`` (device, hlo_text, ledger, ...)."""
    from thunder_tpu.observability import roofline as roofline_mod

    return roofline_mod.enable(jfn, every=every, **options)


def roofline_report(top_k: int = 10) -> Optional[str]:
    """The live roofline ledger as a printable table (None when no sampler
    is installed) — the in-process spelling of ``/debug/roofline``."""
    from thunder_tpu.observability import roofline as roofline_mod

    sampler = roofline_mod.current()
    return sampler.ledger.format(top_k) if sampler is not None else None


def shutdown_roofline() -> None:
    """Uninstall the process-wide roofline sampler."""
    from thunder_tpu.observability import roofline as roofline_mod

    roofline_mod.disable()


def critpath(**options):
    """Arm the fleet critical-path timeline recorder (ISSUE 20): per-step
    host spans fold into a skew-aligned fleet timeline whose critical path
    decomposes into typed classes (compute / exposed-ICI / exposed-DCN /
    straggler-wait / stall / idle), exported as
    ``thunder_tpu_critpath_fraction{class=}`` gauges and streamed into the
    ops-plane detectors as ``bottleneck_shift`` anomalies. Fleet drivers
    feed the returned recorder (``record_step``, ``note_collective`` —
    ``resilience/federation.run_federated_training`` does this when handed
    ``timeline=``); ``options`` forward to
    ``observability.timeline.enable`` (bank, emulated_skew_s, ...)."""
    from thunder_tpu.observability import timeline as timeline_mod

    return timeline_mod.enable(**options)


def critpath_report() -> Optional[str]:
    """The live fleet critical-path ledger as a printable report (EWMA
    class fractions + trend, per-host clock-skew estimates with
    confidence, the static-vs-measured exposed-collective cross-check) —
    the in-process spelling of ``/debug/critpath``. None when no timeline
    recorder is installed."""
    from thunder_tpu.observability import timeline as timeline_mod

    recorder = timeline_mod.current()
    return recorder.format_report() if recorder is not None else None


def shutdown_critpath() -> None:
    """Uninstall the process-wide timeline recorder."""
    from thunder_tpu.observability import timeline as timeline_mod

    timeline_mod.disable()
