"""The core tensor language ("clang").

Reference parity: thunder/clang/__init__.py (113 `@clangop`s) — the
device-agnostic tensor language sitting between the torch-mirror layer and
prims. clang ops are plain Python functions (not symbols): they perform
broadcasting, Python-number/type promotion, dtype conversion, and index
canonicalization, then decompose into strict prims. Their calls inline into
the enclosing symbol's subsymbol scope.
"""

from __future__ import annotations

from functools import reduce
from numbers import Number
from typing import Any, Optional, Sequence

import thunder_tpu.core.prims as prims
from thunder_tpu.core import dtypes, devices, utils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.langctxs import LanguageContext, Languages, register_langctx
from thunder_tpu.core.proxies import NumberProxy, TensorProxy, pyval
from thunder_tpu.core.utils import ELEMENTWISE_TYPE_PROMOTION_KIND as _K


_clang_ctx = LanguageContext(Languages.CLANG)
register_langctx(Languages.CLANG, _clang_ctx)

_method_names: dict[str, str] = {}


def clangop(method_name: Optional[str] = None):
    def decorator(fn):
        if method_name is not None:
            _clang_ctx.register_method(method_name, fn)
        return fn

    return decorator


# =============================================================================
# dtype and broadcasting helpers
# =============================================================================


def maybe_convert_to_dtype(a, dtype: dtypes.dtype):
    """Convert tensor/number to dtype if it differs (no-op otherwise)."""
    if isinstance(a, TensorProxy):
        if a.dtype != dtypes.to_strong(dtype):
            return prims.convert_element_type(a, dtypes.to_strong(dtype))
        return a
    # numbers
    typ = dtypes.dtype_to_numbertype(dtype)
    v = pyval(a)
    if v is not None and not isinstance(a, TensorProxy):
        return typ(v)
    return prims.convert_element_type(a, dtype)


@clangop()
def maybe_broadcast(*args):
    """Broadcast tensor args to their common shape (numbers pass through)."""
    shapes = [a.shape for a in args if isinstance(a, TensorProxy)]
    if not shapes:
        return args
    common = utils.compute_broadcast_shape(*shapes)

    def _maybe(a):
        if isinstance(a, TensorProxy) and tuple(a.shape) != common:
            return expand_to(a, common)
        return a

    return tuple(_maybe(a) for a in args)


def expand_to(a: TensorProxy, shape: Sequence[int]) -> TensorProxy:
    """Broadcast ``a`` to ``shape`` (right-aligned)."""
    shape = tuple(shape)
    if tuple(a.shape) == shape:
        return a
    offset = len(shape) - a.ndim
    check(offset >= 0, lambda: f"Cannot expand {a.shape} to smaller rank {shape}")
    bdims = tuple(range(offset, len(shape)))
    return prims.broadcast_in_dim(a, shape, bdims)


def _elementwise_binary_wrapper(a, b, *, prim, type_promotion_kind=_K.DEFAULT):
    computation_dtype, result_dtype = utils.elementwise_type_promotion(a, b, type_promotion_kind=type_promotion_kind)
    a, b = maybe_broadcast(a, b)
    if isinstance(a, TensorProxy) or isinstance(b, TensorProxy):
        # Embed numbers as same-dtype scalars via broadcast of a full()
        ta = a if isinstance(a, TensorProxy) else b
        if not isinstance(a, TensorProxy):
            a = full((), maybe_convert_to_dtype(a, computation_dtype), device=ta.device, dtype=computation_dtype)
            a = expand_to(a, ta.shape)
        if not isinstance(b, TensorProxy):
            b = full((), maybe_convert_to_dtype(b, computation_dtype), device=ta.device, dtype=computation_dtype)
            b = expand_to(b, ta.shape)
        a = maybe_convert_to_dtype(a, computation_dtype)
        b = maybe_convert_to_dtype(b, computation_dtype)
    result = prim(a, b)
    if isinstance(result, TensorProxy) and result.dtype != dtypes.to_strong(result_dtype):
        result = maybe_convert_to_dtype(result, result_dtype)
    return result


def _make_elementwise_binary(name: str, prim, *, tpk=_K.DEFAULT, method: Optional[str] = None):
    def op(a, b):
        return _elementwise_binary_wrapper(a, b, prim=prim, type_promotion_kind=tpk)

    op.__name__ = name
    if method:
        _clang_ctx.register_method(method, op)
    return op


def _make_elementwise_unary(name: str, prim, *, tpk=_K.DEFAULT, float_only: bool = False, method: Optional[str] = None):
    def op(a):
        computation_dtype, result_dtype = utils.elementwise_type_promotion(
            a, type_promotion_kind=_K.INT_TO_FLOAT if float_only else tpk
        )
        if isinstance(a, TensorProxy):
            a = maybe_convert_to_dtype(a, computation_dtype)
        result = prim(a)
        if isinstance(result, TensorProxy) and result.dtype != dtypes.to_strong(result_dtype):
            result = maybe_convert_to_dtype(result, result_dtype)
        return result

    op.__name__ = name
    if method:
        _clang_ctx.register_method(method, op)
    return op


# =============================================================================
# Elementwise ops
# =============================================================================

add = _make_elementwise_binary("add", prims.add, method="add")
atan2 = _make_elementwise_binary("atan2", prims.atan2, tpk=_K.INT_TO_FLOAT)
bitwise_and = _make_elementwise_binary("bitwise_and", prims.bitwise_and, method="bitwise_and")
bitwise_or = _make_elementwise_binary("bitwise_or", prims.bitwise_or, method="bitwise_or")
bitwise_xor = _make_elementwise_binary("bitwise_xor", prims.bitwise_xor, method="bitwise_xor")
eq = _make_elementwise_binary("eq", prims.eq, tpk=_K.ALWAYS_BOOL, method="eq")
fmod = _make_elementwise_binary("fmod", prims.fmod)
ge = _make_elementwise_binary("ge", prims.ge, tpk=_K.ALWAYS_BOOL, method="ge")
gt = _make_elementwise_binary("gt", prims.gt, tpk=_K.ALWAYS_BOOL, method="gt")
le = _make_elementwise_binary("le", prims.le, tpk=_K.ALWAYS_BOOL, method="le")
lt = _make_elementwise_binary("lt", prims.lt, tpk=_K.ALWAYS_BOOL, method="lt")
maximum = _make_elementwise_binary("maximum", prims.maximum)
minimum = _make_elementwise_binary("minimum", prims.minimum)
mul = _make_elementwise_binary("mul", prims.mul, method="mul")
ne = _make_elementwise_binary("ne", prims.ne, tpk=_K.ALWAYS_BOOL, method="ne")
nextafter = _make_elementwise_binary("nextafter", prims.nextafter, tpk=_K.INT_TO_FLOAT)
pow = _make_elementwise_binary("pow", prims.pow_prim, method="pow")
remainder = _make_elementwise_binary("remainder", prims.remainder, method="remainder")
sub = _make_elementwise_binary("sub", prims.sub, method="sub")
copysign = _make_elementwise_binary("copysign", prims.copysign, tpk=_K.INT_TO_FLOAT, method="copysign")
zeta = _make_elementwise_binary("zeta", prims.zeta, tpk=_K.INT_TO_FLOAT)
mod = remainder  # reference clang alias (clang/__init__.py `mod`)


@clangop()
def polygamma(n: int, a):
    check(isinstance(n, (int, NumberProxy)) and int(pyval(n)) >= 0, lambda: f"polygamma order must be a non-negative int, got {n}")
    computation_dtype, result_dtype = utils.elementwise_type_promotion(a, type_promotion_kind=_K.INT_TO_FLOAT)
    if isinstance(a, TensorProxy):
        a = maybe_convert_to_dtype(a, computation_dtype)
    return prims.polygamma(int(pyval(n)), a)


@clangop(method_name="logical_and")
def logical_and(a, b):
    return bitwise_and(ne(a, 0) if not _is_bool(a) else a, ne(b, 0) if not _is_bool(b) else b)


@clangop(method_name="logical_or")
def logical_or(a, b):
    return bitwise_or(ne(a, 0) if not _is_bool(a) else a, ne(b, 0) if not _is_bool(b) else b)


def _is_bool(x) -> bool:
    return isinstance(x, TensorProxy) and dtypes.is_boolean_dtype(x.dtype) or isinstance(x, bool)


@clangop(method_name="real")
def real(a):
    """Real part; identity on real-dtype tensors (no op emitted)."""
    if isinstance(a, TensorProxy) and not dtypes.is_complex_dtype(a.dtype):
        return a
    return prims.real(a)


@clangop()
def imag(a):
    return prims.imag(a)


@clangop(method_name="true_divide")
def true_divide(a, b):
    return _elementwise_binary_wrapper(a, b, prim=prims.div, type_promotion_kind=_K.INT_TO_FLOAT)


@clangop(method_name="floor_divide")
def floor_divide(a, b):
    r = _elementwise_binary_wrapper(a, b, prim=prims.div, type_promotion_kind=_K.DEFAULT)
    if isinstance(r, TensorProxy) and dtypes.is_float_dtype(r.dtype):
        return _make_elementwise_unary("floor", prims.floor)(r)
    return r


abs = _make_elementwise_unary("abs", prims.abs_prim, tpk=_K.COMPLEX_TO_FLOAT, method="abs")
acos = _make_elementwise_unary("acos", prims.acos, float_only=True, method="acos")
acosh = _make_elementwise_unary("acosh", prims.acosh, float_only=True)
asin = _make_elementwise_unary("asin", prims.asin, float_only=True, method="asin")
asinh = _make_elementwise_unary("asinh", prims.asinh, float_only=True)
atan = _make_elementwise_unary("atan", prims.atan, float_only=True, method="atan")
atanh = _make_elementwise_unary("atanh", prims.atanh, float_only=True)
bitwise_not = _make_elementwise_unary("bitwise_not", prims.bitwise_not, method="bitwise_not")
ceil = _make_elementwise_unary("ceil", prims.ceil, method="ceil")
cos = _make_elementwise_unary("cos", prims.cos, float_only=True, method="cos")
cosh = _make_elementwise_unary("cosh", prims.cosh, float_only=True)
digamma = _make_elementwise_unary("digamma", prims.digamma, float_only=True)
erf = _make_elementwise_unary("erf", prims.erf, float_only=True, method="erf")
erfc = _make_elementwise_unary("erfc", prims.erfc, float_only=True)
erfinv = _make_elementwise_unary("erfinv", prims.erfinv, float_only=True)
exp = _make_elementwise_unary("exp", prims.exp, float_only=True, method="exp")
exp2 = _make_elementwise_unary("exp2", prims.exp2, float_only=True)
expm1 = _make_elementwise_unary("expm1", prims.expm1, float_only=True)
floor = _make_elementwise_unary("floor", prims.floor, method="floor")
isfinite = _make_elementwise_unary("isfinite", prims.isfinite, tpk=_K.ALWAYS_BOOL)
isinf = _make_elementwise_unary("isinf", prims.isinf, tpk=_K.ALWAYS_BOOL)
isnan = _make_elementwise_unary("isnan", prims.isnan, tpk=_K.ALWAYS_BOOL)
lgamma = _make_elementwise_unary("lgamma", prims.lgamma, float_only=True)
log = _make_elementwise_unary("log", prims.log, float_only=True, method="log")
log10 = _make_elementwise_unary("log10", prims.log10, float_only=True)
log1p = _make_elementwise_unary("log1p", prims.log1p, float_only=True)
log2 = _make_elementwise_unary("log2", prims.log2, float_only=True)
neg = _make_elementwise_unary("neg", prims.neg, method="neg")
reciprocal = _make_elementwise_unary("reciprocal", prims.reciprocal, float_only=True, method="reciprocal")
round = _make_elementwise_unary("round", prims.round_prim, method="round")
rsqrt = _make_elementwise_unary("rsqrt", prims.rsqrt, float_only=True, method="rsqrt")
sign = _make_elementwise_unary("sign", prims.sign)
signbit = _make_elementwise_unary("signbit", prims.signbit, tpk=_K.ALWAYS_BOOL)
sin = _make_elementwise_unary("sin", prims.sin, float_only=True, method="sin")
sinh = _make_elementwise_unary("sinh", prims.sinh, float_only=True)
sqrt = _make_elementwise_unary("sqrt", prims.sqrt, float_only=True, method="sqrt")
tan = _make_elementwise_unary("tan", prims.tan, float_only=True)
tanh = _make_elementwise_unary("tanh", prims.tanh, float_only=True, method="tanh")
trunc = _make_elementwise_unary("trunc", prims.trunc)


@clangop(method_name="logical_not")
def logical_not(a):
    if isinstance(a, TensorProxy) and dtypes.is_boolean_dtype(a.dtype):
        return bitwise_not(a)
    return eq(a, 0)


@clangop()
def where(pred, a, b):
    computation_dtype, result_dtype = utils.elementwise_type_promotion(a, b, type_promotion_kind=_K.PRESERVE)
    pred, a, b = maybe_broadcast(pred, a, b)
    ref = next(x for x in (pred, a, b) if isinstance(x, TensorProxy))
    if not isinstance(pred, TensorProxy):
        pred = full((), bool(pyval(pred)), device=ref.device, dtype=dtypes.bool8)
        pred = expand_to(pred, ref.shape)
    if not isinstance(a, TensorProxy):
        a = expand_to(full((), maybe_convert_to_dtype(a, computation_dtype), device=ref.device, dtype=computation_dtype), ref.shape)
    if not isinstance(b, TensorProxy):
        b = expand_to(full((), maybe_convert_to_dtype(b, computation_dtype), device=ref.device, dtype=computation_dtype), ref.shape)
    a = maybe_convert_to_dtype(a, computation_dtype)
    b = maybe_convert_to_dtype(b, computation_dtype)
    return prims.where(pred, a, b)


@clangop(method_name="clamp")
def clamp(a, min=None, max=None):
    r = a
    if min is not None:
        r = maximum(r, min)
    if max is not None:
        r = minimum(r, max)
    return r


# =============================================================================
# Creation
# =============================================================================


@clangop()
def full(shape, fill_value, *, device=None, dtype=None):
    device = devices.to_device(device) if device is not None else devices.Device()
    if dtype is None:
        dtype = dtypes.to_strong(dtypes.numbertype_to_dtype(type(pyval(fill_value))))
        if dtype == dtypes.float64:
            dtype = dtypes.float32
    return prims.full(tuple(shape), pyval(fill_value), device=device, dtype=dtypes.to_strong(dtype))


@clangop()
def full_like(a, fill_value, *, device=None, dtype=None):
    return full(
        a.shape,
        fill_value,
        device=device if device is not None else a.device,
        dtype=dtype if dtype is not None else a.dtype,
    )


@clangop()
def zeros(shape, *, device=None, dtype=None):
    return full(shape, 0.0 if dtype is None or dtypes.is_inexact_dtype(dtypes.to_dtype(dtype)) else 0, device=device, dtype=dtype or dtypes.float32)


@clangop()
def ones(shape, *, device=None, dtype=None):
    return full(shape, 1.0 if dtype is None or dtypes.is_inexact_dtype(dtypes.to_dtype(dtype)) else 1, device=device, dtype=dtype or dtypes.float32)


@clangop()
def zeros_like(a, *, device=None, dtype=None):
    return full_like(a, 0 if dtypes.is_exact_dtype(a.dtype) and dtype is None else 0.0, device=device, dtype=dtype)


@clangop()
def ones_like(a, *, device=None, dtype=None):
    return full_like(a, 1 if dtypes.is_exact_dtype(a.dtype) and dtype is None else 1.0, device=device, dtype=dtype)


@clangop()
def arange(start, end=None, step=1, *, device=None, dtype=None):
    if end is None:
        start, end = 0, start
    device = devices.to_device(device) if device is not None else devices.Device()
    start_v, end_v, step_v = pyval(start), pyval(end), pyval(step)
    check(step_v != 0, "arange step must be nonzero")
    import math

    length = max(0, math.ceil((end_v - start_v) / step_v))
    if dtype is None:
        if any(isinstance(v, float) for v in (start_v, end_v, step_v)):
            dtype = dtypes.float32
        else:
            dtype = dtypes.int64
    return prims.iota(length, start=start_v, step=step_v, device=device, dtype=dtypes.to_strong(dtypes.to_dtype(dtype)))


@clangop()
def uniform(shape, minval=0.0, maxval=1.0, *, device=None, dtype=None):
    device = devices.to_device(device) if device is not None else devices.Device()
    dtype = dtypes.to_strong(dtypes.to_dtype(dtype)) if dtype is not None else dtypes.float32
    return prims.uniform(tuple(shape), pyval(minval), pyval(maxval), device=device, dtype=dtype)


@clangop()
def randn(shape, *, device=None, dtype=None):
    device = devices.to_device(device) if device is not None else devices.Device()
    dtype = dtypes.to_strong(dtypes.to_dtype(dtype)) if dtype is not None else dtypes.float32
    return prims.randn(tuple(shape), device=device, dtype=dtype)


@clangop()
def tensor_from_sequence(seq, *, device=None, dtype=None):
    device = devices.to_device(device) if device is not None else devices.Device()
    return prims.tensor_from_sequence(seq, device=device, dtype=dtype)


@clangop()
def diagonal_mask(n: int, m: int, *, offset: int = 0, upper: bool = True, device=None):
    """Boolean mask selecting the upper/lower triangle — building block for
    tril/triu/causal masks (reference: clang's tril/triu decomposition)."""
    device = devices.to_device(device) if device is not None else devices.Device()
    rows = prims.iota(n, start=0, step=1, device=device, dtype=dtypes.int32)
    cols = prims.iota(m, start=0, step=1, device=device, dtype=dtypes.int32)
    rows = prims.broadcast_in_dim(rows, (n, m), (0,))
    cols = prims.broadcast_in_dim(cols, (n, m), (1,))
    if upper:
        return ge(sub(cols, rows), offset)
    return le(sub(cols, rows), offset)


# =============================================================================
# dtype / device movement
# =============================================================================


@clangop(method_name="to")
def to(a, device=None, dtype=None):
    if dtype is not None:
        a = maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    if device is not None and isinstance(a, TensorProxy):
        device = devices.to_device(device)
        if device != a.device:
            a = prims.device_put(a, device)
    return a


@clangop(method_name="type_as")
def type_as(a, b):
    return maybe_convert_to_dtype(a, b.dtype)


@clangop(method_name="item")
def item(a):
    return prims.item(a)


# =============================================================================
# Shape ops
# =============================================================================


@clangop(method_name="reshape")
def reshape(a, shape):
    shape = tuple(int(pyval(s)) for s in shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        inferred = a.numel // known
        shape = tuple(inferred if s == -1 else s for s in shape)
    if tuple(a.shape) == shape:
        return a
    return prims.reshape(a, shape)


@clangop(method_name="expand")
def expand(a, shape):
    shape = tuple(int(pyval(s)) for s in shape)
    offset = len(shape) - a.ndim
    shape = tuple(a.shape[i - offset] if s == -1 else s for i, s in enumerate(shape))
    return expand_to(a, shape)


@clangop(method_name="permute")
def permute(a, permutation):
    permutation = utils.canonicalize_dims(a.ndim, tuple(int(pyval(p)) for p in permutation))
    if permutation == tuple(range(a.ndim)):
        return a
    return prims.transpose(a, permutation)


@clangop(method_name="transpose")
def transpose(a, dim0: int, dim1: int):
    dim0 = utils.canonicalize_dim(a.ndim, dim0)
    dim1 = utils.canonicalize_dim(a.ndim, dim1)
    perm = list(range(a.ndim))
    perm[dim0], perm[dim1] = perm[dim1], perm[dim0]
    return permute(a, perm)


@clangop()
def matrix_transpose(a):
    check(a.ndim >= 2, "matrix_transpose requires rank >= 2")
    return transpose(a, -2, -1)


@clangop(method_name="movedim")
def movedim(a, source, destination):
    src = utils.canonicalize_dims(a.ndim, source if isinstance(source, (tuple, list)) else (source,))
    dst = utils.canonicalize_dims(a.ndim, destination if isinstance(destination, (tuple, list)) else (destination,))
    perm = [d for d in range(a.ndim) if d not in src]
    for s, d in sorted(zip(src, dst), key=lambda x: x[1]):
        perm.insert(d, s)
    return permute(a, perm)


@clangop(method_name="squeeze")
def squeeze(a, dims=None):
    if dims is None:
        dims = tuple(i for i, s in enumerate(a.shape) if s == 1)
    else:
        if isinstance(dims, int):
            dims = (dims,)
        dims = utils.canonicalize_dims(a.ndim, dims)
        dims = tuple(d for d in dims if a.shape[d] == 1)
    if not dims:
        return a
    return prims.squeeze(a, dims)


@clangop(method_name="unsqueeze")
def unsqueeze(a, dim: int):
    dim = utils.canonicalize_dim(a.ndim + 1, dim)
    shape = list(a.shape)
    shape.insert(dim, 1)
    return prims.reshape(a, tuple(shape))


@clangop(method_name="flatten")
def flatten(a, start_dim: int = 0, end_dim: int = -1):
    start_dim = utils.canonicalize_dim(a.ndim, start_dim)
    end_dim = utils.canonicalize_dim(a.ndim, end_dim)
    if a.ndim == 0:
        return reshape(a, (1,))
    mid = 1
    for s in a.shape[start_dim : end_dim + 1]:
        mid *= s
    shape = a.shape[:start_dim] + (mid,) + a.shape[end_dim + 1 :]
    return reshape(a, shape)


@clangop()
def stride_order(a, order=None):
    # Layout is XLA's concern on TPU; identity for parity.
    return a


@clangop()
def cat(tensors, dim: int = 0):
    tensors = list(tensors)
    check(len(tensors) > 0, "cat of empty list")
    if len(tensors) == 1:
        return tensors[0]
    st = reduce(lambda x, y: _promote_tensors(x, y), tensors)
    tensors = [maybe_convert_to_dtype(t, st) for t in tensors]
    return prims.cat(tensors, utils.canonicalize_dim(tensors[0].ndim, dim))


def _promote_tensors(x, y):
    if isinstance(x, dtypes.dtype):
        dx = x
    else:
        dx = x.dtype
    _, result = utils.elementwise_type_promotion(
        TensorProxy(shape=(), dtype=dx, device=(y.device if isinstance(y, TensorProxy) else devices.cpu)),
        y,
        type_promotion_kind=_K.PRESERVE,
    )
    return result


@clangop()
def stack(tensors, dim: int = 0):
    tensors = [unsqueeze(t, dim) for t in tensors]
    return cat(tensors, dim)


@clangop(method_name="chunk")
def chunk(a, chunks: int, dim: int = 0):
    dim = utils.canonicalize_dim(a.ndim, dim)
    size = a.shape[dim]
    chunk_size = (size + chunks - 1) // chunks
    return split(a, chunk_size, dim)


@clangop(method_name="split")
def split(a, split_size_or_sections, dim: int = 0):
    dim = utils.canonicalize_dim(a.ndim, dim)
    size = a.shape[dim]
    if isinstance(split_size_or_sections, int):
        sections = []
        pos = 0
        while pos < size:
            sections.append(min(split_size_or_sections, size - pos))
            pos += split_size_or_sections
    else:
        sections = list(split_size_or_sections)
    outs = []
    pos = 0
    for s in sections:
        outs.append(slice_in_dim(a, pos, pos + s, dim=dim))
        pos += s
    return tuple(outs)


@clangop()
def slice_in_dim(a, start: int, end: int, *, stride: int = 1, dim: int = 0):
    dim = utils.canonicalize_dim(a.ndim, dim)
    starts = [0] * a.ndim
    ends = list(a.shape)
    strides = [1] * a.ndim
    start = max(0, start + a.shape[dim] if start < 0 else start)
    end = min(a.shape[dim], end + a.shape[dim] if end < 0 else end)
    end = max(start, end)
    starts[dim] = start
    ends[dim] = end
    strides[dim] = stride
    return prims.slice_prim(a, starts, ends, strides)


@clangop()
def flip(a, dims):
    if isinstance(dims, int):
        dims = (dims,)
    return prims.flip(a, utils.canonicalize_dims(a.ndim, tuple(dims)))


@clangop()
def pad(a, padding_value, padding_config):
    return prims.pad(a, pyval(padding_value), tuple(tuple(p) for p in padding_config))


@clangop(method_name="getitem")
def getitem(a, key):
    """Basic indexing: int / slice / None / Ellipsis / tensor (advanced, via
    take). Reference parity: thunder/clang `_basic_indexing:556` +
    advanced-indexing subset."""
    if not isinstance(key, tuple):
        key = (key,)

    # Advanced indexing with a single integer tensor (common embedding case)
    if len(key) == 1 and isinstance(key[0], TensorProxy):
        idx = key[0]
        flat = reshape(idx, (idx.numel,))
        taken = prims.take(a, flat, 0)
        return reshape(taken, tuple(idx.shape) + tuple(a.shape[1:]))

    # Count specified dims (non-None, non-Ellipsis). Identity checks only:
    # `in`/`==` on a key containing TensorProxies would trace elementwise eq.
    n_spec = len([k for k in key if k is not None and k is not Ellipsis])
    check(n_spec <= a.ndim, "too many indices")
    # Expand Ellipsis
    ell = next((i for i, k in enumerate(key) if k is Ellipsis), None)
    if ell is not None:
        fill = a.ndim - n_spec
        key = key[:ell] + (slice(None),) * fill + key[ell + 1 :]
    else:
        key = key + (slice(None),) * (a.ndim - n_spec)

    # Multi-tensor advanced indexing over every dim (e.g. HF's
    # ``padding_mask[batch_idx, kv_idx]`` with broadcasting index tensors):
    # broadcast the indices together, linearize, and gather from the
    # flattened array.
    if len([k for k in key if isinstance(k, TensorProxy)]) >= 2:  # clang.sum shadows builtins.sum
        check(
            len(key) == a.ndim
            and all(isinstance(k, (TensorProxy, int, NumberProxy)) for k in key),
            lambda: "advanced-indexing subset: multiple tensor indices must cover every dim",
        )
        linear = None
        for k, size in zip(key, a.shape):
            if isinstance(k, TensorProxy):
                kk = where(lt(k, 0), add(k, size), k)
            else:
                kv = int(pyval(k))
                kk = kv + size if kv < 0 else kv
            linear = kk if linear is None else add(mul(linear, size), kk)
        if isinstance(linear, TensorProxy):
            out_shape = tuple(linear.shape)
            flat_idx = reshape(linear, (linear.numel,))
            taken = prims.take(reshape(a, (a.numel,)), flat_idx, 0)
            return reshape(taken, out_shape)
        return getitem(reshape(a, (a.numel,)), linear)

    starts, ends, strides = [], [], []
    squeeze_dims = []  # dims indexed by int → removed
    unsqueeze_positions = []  # positions of None → size-1 dims inserted
    dim = 0
    out_pos = 0
    for k in key:
        if k is None:
            unsqueeze_positions.append(out_pos)
            out_pos += 1
            continue
        size = a.shape[dim]
        if isinstance(k, (int, NumberProxy)):
            kv = int(pyval(k))
            kv = kv + size if kv < 0 else kv
            check(0 <= kv < size, lambda: f"index {k} out of range for dim {dim} of size {size}")
            starts.append(kv)
            ends.append(kv + 1)
            strides.append(1)
            squeeze_dims.append(dim)
            dim += 1
            continue
        if isinstance(k, slice):
            start, stop, stride = k.indices(size)
            check(stride > 0, "negative slice steps unsupported; use flip()")
            starts.append(start)
            ends.append(max(start, stop))
            strides.append(stride)
            dim += 1
            out_pos += 1
            continue
        raise NotImplementedError(f"Unsupported index element {k!r}")

    r = a
    if any(s != 0 for s in starts) or any(e != s for e, s in zip(ends, a.shape)) or any(st != 1 for st in strides):
        r = prims.slice_prim(a, starts, ends, strides)
    if squeeze_dims:
        r = prims.squeeze(r, tuple(squeeze_dims))
    for pos in unsqueeze_positions:
        r = unsqueeze(r, pos)
    return r


@clangop()
def take(a, indices, dim: int = 0):
    return prims.take(a, indices, utils.canonicalize_dim(a.ndim, dim))


@clangop()
def take_along_axis(a, indices, dim: int = 0):
    return prims.take_along_axis(a, indices, utils.canonicalize_dim(a.ndim, dim))


@clangop(method_name="gather")
def gather(a, dim: int, indices):
    return prims.gather(a, indices, utils.canonicalize_dim(a.ndim, dim))


@clangop(method_name="scatter_add")
def scatter_add(a, dim: int, indices, value):
    return prims.scatter_add(a, indices, value, utils.canonicalize_dim(a.ndim, dim))


@clangop(method_name="index_put")
def index_put(a, indices, values, accumulate: bool = False):
    return prims.index_put(a, tuple(indices), values, accumulate)


@clangop()
def tril(a, diagonal: int = 0):
    check(a.ndim >= 2, "tril requires rank >= 2")
    mask = diagonal_mask(a.shape[-2], a.shape[-1], offset=diagonal, upper=False, device=a.device)
    mask = expand_to(mask, a.shape)
    return where(mask, a, zeros_like(a))


@clangop()
def triu(a, diagonal: int = 0):
    check(a.ndim >= 2, "triu requires rank >= 2")
    mask = diagonal_mask(a.shape[-2], a.shape[-1], offset=diagonal, upper=True, device=a.device)
    mask = expand_to(mask, a.shape)
    return where(mask, a, zeros_like(a))


# =============================================================================
# Reductions
# =============================================================================


def _reduction_dims(ndim: int, dims) -> tuple:
    if dims is None:
        return tuple(range(ndim))
    if isinstance(dims, int):
        dims = (dims,)
    return utils.canonicalize_dims(ndim, tuple(dims))


def _maybe_keepdim(r, orig_shape, dims, keepdim: bool):
    if not keepdim:
        return r
    shape = list(orig_shape)
    for d in dims:
        shape[d] = 1
    return reshape(r, tuple(shape))


def _make_reduction(name: str, prim, *, method=None):
    def op(a, dims=None, keepdim: bool = False):
        rdims = _reduction_dims(a.ndim, dims)
        r = prim(a, rdims)
        return _maybe_keepdim(r, a.shape, rdims, keepdim)

    op.__name__ = name
    if method:
        _clang_ctx.register_method(method, op)
    return op


amax = _make_reduction("amax", prims.amax, method="amax")
amin = _make_reduction("amin", prims.amin, method="amin")
prod = _make_reduction("prod", prims.prod, method="prod")


@clangop(method_name="sum")
def sum(a, dims=None, keepdim: bool = False, *, dtype=None):
    rdims = _reduction_dims(a.ndim, dims)
    if dtype is not None:
        a = maybe_convert_to_dtype(a, dtypes.to_dtype(dtype))
    elif dtypes.is_boolean_dtype(a.dtype):
        a = maybe_convert_to_dtype(a, dtypes.int64)
    r = prims.sum_prim(a, rdims)
    return _maybe_keepdim(r, a.shape, rdims, keepdim)


@clangop(method_name="mean")
def mean(a, dims=None, keepdim: bool = False, *, dtype=None):
    rdims = _reduction_dims(a.ndim, dims)
    count = 1
    for d in rdims:
        count *= a.shape[d]
    result_dtype = dtypes.to_dtype(dtype) if dtype is not None else (
        a.dtype if dtypes.is_inexact_dtype(a.dtype) else dtypes.float32
    )
    a = maybe_convert_to_dtype(a, result_dtype)
    r = sum(a, rdims, keepdim)
    return true_divide(r, count)


@clangop(method_name="var")
def var(a, dims=None, *, correction: Number = 1, keepdim: bool = False):
    rdims = _reduction_dims(a.ndim, dims)
    r = prims.var(a, rdims, correction=correction)
    return _maybe_keepdim(r, a.shape, rdims, keepdim)


@clangop()
def var_mean(a, dims=None, *, correction: Number = 1, keepdim: bool = False):
    rdims = _reduction_dims(a.ndim, dims)
    v, m = prims.var_mean(a, rdims, correction=correction)
    return _maybe_keepdim(v, a.shape, rdims, keepdim), _maybe_keepdim(m, a.shape, rdims, keepdim)


@clangop(method_name="std")
def std(a, dims=None, *, correction: Number = 1, keepdim: bool = False):
    return sqrt(var(a, dims, correction=correction, keepdim=keepdim))


@clangop(method_name="argmax")
def argmax(a, dim=None, keepdim: bool = False):
    r = prims.argmax(a, dim)
    if keepdim and dim is not None:
        r = unsqueeze(r, utils.canonicalize_dim(a.ndim, dim))
    return r


@clangop(method_name="argmin")
def argmin(a, dim=None, keepdim: bool = False):
    r = prims.argmin(a, dim)
    if keepdim and dim is not None:
        r = unsqueeze(r, utils.canonicalize_dim(a.ndim, dim))
    return r


@clangop(method_name="all")
def all_tensor(a, dims=None, keepdim: bool = False):
    r = logical_not(any_tensor(logical_not(a), dims, keepdim))
    return r


@clangop(method_name="any")
def any_tensor(a, dims=None, keepdim: bool = False):
    b = maybe_convert_to_dtype(ne(a, 0) if not dtypes.is_boolean_dtype(a.dtype) else a, dtypes.int64)
    return ne(sum(b, dims, keepdim), 0)


# =============================================================================
# Linear algebra / NN
# =============================================================================


@clangop(method_name="matmul")
def matmul(a, b):
    # Promote to a common dtype, then call the strict prim.
    _, result_dtype = utils.elementwise_type_promotion(a, b, type_promotion_kind=_K.PRESERVE)
    a = maybe_convert_to_dtype(a, result_dtype)
    b = maybe_convert_to_dtype(b, result_dtype)
    return prims.matmul(a, b)


@clangop()
def linear(a, w, bias=None):
    return prims.linear(a, w, bias)


@clangop()
def convolution(a, weight, bias, stride, padding, dilation, groups: int):
    return prims.convolution(a, weight, bias, tuple(stride), tuple(padding), tuple(dilation), int(groups))


@clangop()
def embedding(indices, weight):
    return prims.embedding(indices, weight)


@clangop()
def stop_gradient(a):
    return prims.stop_gradient(a)


@clangop(method_name="cumsum")
def cumsum(a, dim: int):
    return prims.cumsum(a, utils.canonicalize_dim(a.ndim, dim))


@clangop()
def topk(a, k: int, dim: int = -1, largest: bool = True, sorted: bool = True):
    return prims.topk(a, int(pyval(k)), utils.canonicalize_dim(a.ndim, dim), bool(largest), bool(sorted))


@clangop()
def sort(a, dim: int = -1, descending: bool = False):
    return prims.sort(a, utils.canonicalize_dim(a.ndim, dim), bool(descending))


@clangop()
def argsort(a, dim: int = -1, descending: bool = False):
    return prims.argsort(a, utils.canonicalize_dim(a.ndim, dim), bool(descending))


# -- reference-parity additions (thunder/clang public surface) ----------------
# Guard/unpack prims are re-exported so clang covers the reference's full
# public op list (reference: thunder/clang/__init__.py exposes check_*/
# unpack_* used by prologue construction).

check_tensor_shape_and_metadata = prims.check_tensor_shape_and_metadata
check_number_type_and_value = prims.check_number_type_and_value
check_string_value = prims.check_string_value
check_none = prims.check_none
check_len = prims.check_len
device_put = prims.device_put
unpack_sequence = prims.unpack_sequence
unpack_key = prims.unpack_key


# One broadcast-rule implementation for the whole stack (core/utils.py is
# what maybe_broadcast already consults).
compute_broadcast_shape = utils.compute_broadcast_shape


@clangop()
def sigmoid(a):
    # 1 / (1 + exp(-x)) — the simple composition; XLA fuses it to its
    # logistic lowering, which handles the large-|x| tails.
    return true_divide(1.0, add(exp(neg(a)), 1.0))


@clangop()
def silu(a):
    return mul(a, sigmoid(a))


@clangop()
def diagonal(a, offset: int = 0, dim1: int = 0, dim2: int = 1):
    """Torch-semantics diagonal: move (dim1, dim2) last, gather the diagonal
    along the joint index (the canonical decomposition; ltorch delegates
    here)."""
    from thunder_tpu.core import dtypes as _dt
    from thunder_tpu.core.baseutils import check as _check

    d1 = utils.canonicalize_dim(a.ndim, int(pyval(dim1)))
    d2 = utils.canonicalize_dim(a.ndim, int(pyval(dim2)))
    _check(d1 != d2, "diagonal dims must differ")
    k = int(pyval(offset))
    n, m = a.shape[d1], a.shape[d2]
    length = max(0, min(n, m - k) if k >= 0 else min(n + k, m))
    x = movedim(a, (d1, d2), (a.ndim - 2, a.ndim - 1))
    rows = arange(0, length, 1, device=a.device, dtype=_dt.int64)
    if k >= 0:
        ridx, cidx = rows, add(rows, k)
    else:
        ridx, cidx = add(rows, -k), rows
    x = prims.take(x, ridx, x.ndim - 2)
    cidx_full = expand_to(
        reshape(cidx, (1,) * (x.ndim - 2) + (length, 1)), tuple(x.shape[:-1]) + (1,)
    )
    return squeeze(take_along_axis(x, cidx_full, x.ndim - 1), (x.ndim - 1,))


def _index_to_scatter_idx(a, d: int, index, source):
    """(n,) index vector → scatter_add-shaped index matching ``source``."""
    return expand_to(
        reshape(index, (1,) * d + (index.shape[0],) + (1,) * (a.ndim - d - 1)),
        tuple(source.shape),
    )


@clangop()
def index_add(a, dim: int, index, source, alpha=1):
    """The canonical index_add decomposition (ltorch delegates here)."""
    d = utils.canonicalize_dim(a.ndim, int(pyval(dim)))
    if pyval(alpha) != 1:
        source = mul(source, alpha)
    return scatter_add(a, d, _index_to_scatter_idx(a, d, index, source), source)


@clangop()
def index_copy(a, dim: int, index, source):
    """scatter-set = scatter_add of (source - current values at index)."""
    d = utils.canonicalize_dim(a.ndim, int(pyval(dim)))
    idx = _index_to_scatter_idx(a, d, index, source)
    current = gather(a, d, idx)
    return scatter_add(a, d, idx, sub(source, current))


@clangop()
def erfcinv(a):
    """Inverse complementary error function: erfinv(1 - a)."""
    return erfinv(sub(1.0, a))


@clangop()
def ndtri(a):
    """Inverse standard-normal CDF: -sqrt(2)·erfinv(1 - 2a) (scipy.special
    ndtri semantics, the reference's clang op)."""
    return mul(erfinv(sub(mul(a, 2.0), 1.0)), 1.4142135623730951)


@clangop()
def uniform_like(a, minval=0.0, maxval=1.0, *, device=None, dtype=None):
    return uniform(tuple(a.shape), minval, maxval,
                   device=device or a.device, dtype=dtype or a.dtype)
