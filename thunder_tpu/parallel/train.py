"""Sharded training steps: trace-compiled fw+bw staged under one pjit.

Reference parity: the end-to-end training loops of the reference's
benchmark/examples (thunder/benchmarks/benchmark_litgpt.py,
examples/lit-gpt/train_fsdp.py) — forward+backward through the compiler,
optimizer outside the trace (the reference leaves the optimizer to the user;
here it is a pure-jax AdamW *inside the same jit* so the whole step is one
XLA executable: fw, bw, grad reduction, and update fuse and overlap under
the latency-hiding scheduler, the TPU answer to `sort_waits` +
CUDAGraphExecutor).

All shardings are `NamedSharding`s over the caller's mesh; optimizer state
inherits the param specs, giving ZeRO-sharded optimizer states for free.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

from thunder_tpu.core.pytree import tree_flatten, tree_map, tree_unflatten
from thunder_tpu.models.gpt import GPTConfig, loss_fn


# =============================================================================
# AdamW (pure jax, pytree-structured)
# =============================================================================


def adamw_init(params):
    import jax.numpy as jnp

    zeros = tree_map(lambda p: jnp.zeros_like(p), params)
    return {"step": jnp.zeros((), dtype=jnp.int32), "m": zeros, "v": tree_map(lambda p: jnp.zeros_like(p), params)}


def opt_state_specs(param_specs, optimizer: str = "adamw"):
    """PartitionSpec pytree for the optimizer state matching
    :func:`adamw_init`'s structure: moments inherit the param specs (ZeRO
    sharding for free), the step counter replicates. The elastic-resume
    path (``resilience/elastic.py``) reshards saved optimizer state through
    exactly these specs, so they live here next to the init."""
    from jax.sharding import PartitionSpec

    if optimizer == "sgd":
        return {"step": PartitionSpec()}
    return {"step": PartitionSpec(), "m": param_specs, "v": param_specs}


def adamw_update(params, grads, state, *, lr=1e-3, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.0):
    import jax.numpy as jnp

    step = state["step"] + 1
    t = step.astype(jnp.float32)
    c1 = 1.0 - jnp.power(b1, t)
    c2 = 1.0 - jnp.power(b2, t)

    def upd(p, g, m, v):
        # Moments in the grad dtype (f32 grads → f32 moments).
        g = g.astype(m.dtype) if g.dtype != m.dtype else g
        m_new = b1 * m + (1.0 - b1) * g
        v_new = b2 * v + (1.0 - b2) * (g * g)
        update = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
        if weight_decay:
            update = update + weight_decay * p.astype(update.dtype)
        return (p - lr * update.astype(p.dtype)), m_new, v_new

    flat_p, spec = tree_flatten(params)
    flat_g, _ = tree_flatten(grads)
    flat_m, _ = tree_flatten(state["m"])
    flat_v, _ = tree_flatten(state["v"])
    out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = tree_unflatten(spec, [o[0] for o in out])
    new_m = tree_unflatten(spec, [o[1] for o in out])
    new_v = tree_unflatten(spec, [o[2] for o in out])
    return new_p, {"step": step, "m": new_m, "v": new_v}


# =============================================================================
# Sharded train step
# =============================================================================


def _compile_loss_and_grads(config: GPTConfig, params, idx, targets, executors=None,
                            *, mesh=None, param_specs=None, comm_schedule=True):
    """Trace loss_fn through the framework pipeline → a pure jax callable
    taking the flat tensor leaves and returning (loss, grads_tuple).

    ``comm_schedule`` runs the certificate-driven collective-overlap
    scheduler (transforms/comm_schedule.py) over the claimed joint trace —
    a no-op when the trace routes its collectives through the SPMD
    partitioner instead of dist_prims, so the pjit path keeps its exact
    program; trace-level FSDP/TP steps get their gathers prefetched. The
    mesh/param_specs (when given) divide sharded inputs so the scheduler's
    liveness back-off prices per-device bytes."""
    from thunder_tpu.api import trace_program
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.attention_residuals import save_sdpa_residuals_joint
    from thunder_tpu.transforms.autodiff import grad_transform
    from thunder_tpu.transforms.common import dce

    ex_list = resolve_executors(executors)
    fn = lambda p, i, t: loss_fn(p, i, t, config)  # noqa: E731
    _, comp = trace_program(fn, (params, idx, targets), {})
    comp = dce(comp)
    joint = grad_transform(comp, return_value=True)
    joint = save_sdpa_residuals_joint(joint, ex_list)
    divisors = None
    if mesh is not None and param_specs is not None:
        from thunder_tpu.analysis.liveness import arg_divisors_from_specs

        try:
            # The joint trace shares its args with the claimed trace, so
            # the divisors computed here hold for the scheduler's input.
            divisors = arg_divisors_from_specs(joint, param_specs, mesh=mesh)
        except Exception:  # noqa: BLE001 — divisors refine, never gate
            divisors = None
    extrace = transform_for_execution(
        joint, ex_list,
        comm_schedule=comm_schedule,
        comm_schedule_opts={"arg_divisors": divisors} if divisors else None,
    )
    return extrace.python_callable(), extrace


def build_train_step(
    config: GPTConfig,
    params,
    idx,
    targets,
    *,
    mesh=None,
    param_specs=None,
    batch_spec=None,
    lr: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grads_in_f32: bool = True,
    donate: bool = True,
    executors=None,
    optimizer: str = "adamw",
    return_extrace: bool = False,
):
    """Compile one full training step (fw+bw+AdamW) as a single sharded XLA
    executable. Returns ``(step_fn, opt_state)``;
    ``step_fn(params, opt_state, idx, targets) -> (params, opt_state, loss)``.

    ``return_extrace=True`` appends the claimed joint execution trace to the
    return tuple — the cost-model input for multichip MFU accounting
    (``scripts/bench_multichip.py`` prices its FLOPs/collectives against the
    device spec via ``analysis.cost.trace_cost``).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    loss_and_grads, extrace = _compile_loss_and_grads(
        config, params, idx, targets, executors=executors,
        mesh=mesh, param_specs=param_specs,
    )

    def step(params, opt_state, idx, targets):
        flat, _ = tree_flatten(((params, idx, targets), {}))
        loss, grads = loss_and_grads(*flat)
        if grads_in_f32:
            grads = tuple(g.astype(jnp.float32) for g in grads)
        p_flat, p_spec = tree_flatten(params)
        grads_tree = tree_unflatten(p_spec, list(grads))
        if optimizer == "sgd":
            # bf16-true SGD(wd) — no moment state; what lets multi-GB models
            # train on one 16 GB chip (the bench.py protocol)
            new_params = tree_map(
                lambda p, g: (p - lr * (g.astype(p.dtype) + weight_decay * p)).astype(p.dtype),
                params, grads_tree,
            )
            return new_params, opt_state, loss
        new_params, new_state = adamw_update(
            params, grads_tree, opt_state, lr=lr, b1=b1, b2=b2, weight_decay=weight_decay
        )
        return new_params, new_state, loss

    opt_state = adamw_init(params) if optimizer != "sgd" else {"step": 0}

    # Donation metadata for the static planner suite (ISSUE 10): the param
    # leaves of the claimed trace are the donated buffers, so the liveness
    # planner frees them at last use, and the donation sanitizer rules
    # (analysis/rules.py donation.*) can check the SDC/rerun invariants
    # statically. donate_argnums=(0, 1) ALSO donates the optimizer state,
    # but the opt update is staged in the outer `step` jit, OUTSIDE the
    # claimed trace — opt leaves have no trace-level proxies to tag, so the
    # trace metadata covers exactly the donated buffers the trace can see
    # (params); the step-level invariant (SDC re-run needs the whole
    # previous state alive) is carried by _thunder_donates on the callable,
    # which run_training checks up front.
    if donate:
        from thunder_tpu.core.proxies import TensorProxy

        n_params = len(tree_flatten(params)[0])
        extrace.tags["donated_inputs"] = tuple(
            a.name for a in extrace.args[:n_params] if isinstance(a, TensorProxy)
        )

    def _stamp(jfn):
        try:
            jfn._thunder_donates = bool(donate)
        except Exception:  # jit wrapper without attribute support
            pass
        return jfn

    if mesh is None:
        jfn = _stamp(jax.jit(step, donate_argnums=(0, 1) if donate else ()))
        return (jfn, opt_state, extrace) if return_extrace else (jfn, opt_state)

    from thunder_tpu.parallel.sharding import data_spec as _dspec

    batch_spec = batch_spec if batch_spec is not None else _dspec(mesh)
    ps = param_specs

    def ns(spec_tree):
        return tree_map(lambda s: NamedSharding(mesh, s), spec_tree,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))

    param_sh = ns(ps)
    opt_sh = ns(opt_state_specs(ps, optimizer))
    data_sh = NamedSharding(mesh, batch_spec)
    loss_sh = NamedSharding(mesh, PartitionSpec())

    jfn = _stamp(jax.jit(
        step,
        in_shardings=(param_sh, opt_sh, data_sh, data_sh),
        out_shardings=(param_sh, opt_sh, loss_sh),
        donate_argnums=(0, 1) if donate else (),
    ))
    return (jfn, opt_state, extrace) if return_extrace else (jfn, opt_state)
