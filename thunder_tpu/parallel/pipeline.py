"""Pipeline parallelism: GPipe-style microbatch pipelining over a ``pp`` axis.

The reference has **no** pipeline parallelism (SURVEY.md §2.3: PP absent) —
here it is a ~60-line differentiable schedule because the TPU mapping is
natural: stages live on consecutive devices along the ``pp`` mesh axis,
activations hop stage→stage with ``ppermute`` (nearest-neighbour ICI), and
the whole schedule is one ``lax.scan`` — a single compiled program, no
per-microbatch host dispatch.

Semantics: ``n_micro`` microbatches flow through ``n_stages`` stages in
``n_micro + n_stages − 1`` ticks (the classic GPipe fill/steady/drain
schedule). Every op used (scan, ppermute, dynamic slicing, where-masking)
has a transpose rule, so ``jax.grad`` through ``pipeline_apply`` IS
pipeline-parallel backprop — the backward replays the schedule in reverse
with cotangents hopping the ring the other way.
"""

from __future__ import annotations


def pipeline_apply(stage_fn, local_params, xs, axis_name: str):
    """Run ``stage_fn`` as a pipeline over the ``axis_name`` mesh axis.

    Inside ``shard_map``:
      stage_fn: (params, x) -> y with x/y of identical shape (stage i
        consumes stage i−1's output).
      local_params: THIS stage's parameter pytree (stack the per-stage
        params outside and shard dim 0 over ``pp``; squeeze before passing).
      xs: (n_micro, mb, ...) the full microbatch stream, replicated — only
        stage 0 reads it.

    Returns (n_micro, mb, ...) outputs, replicated across the axis (zeros
    from non-final stages are psum-combined with the final stage's buffer).
    """
    import jax.numpy as jnp
    from jax import lax

    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = xs.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    act0 = jnp.zeros(xs.shape[1:], xs.dtype)
    outs0 = jnp.zeros_like(xs)

    def tick(carry, t):
        act, outs = carry
        # Activations hop one stage down the ring.
        recv = lax.ppermute(act, axis_name, perm)
        # Stage 0 feeds the next microbatch during the fill/steady phase.
        feed = jnp.where(
            t < n_micro,
            lax.dynamic_index_in_dim(xs, jnp.minimum(t, n_micro - 1), 0, keepdims=False),
            jnp.zeros_like(act0),
        )
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(local_params, x_in)
        # The final stage emits microbatch t − (n_stages − 1) once the
        # pipe is full; earlier ticks and other stages write nothing.
        j = t - (n_stages - 1)
        updated = lax.dynamic_update_index_in_dim(outs, y, jnp.maximum(j, 0), 0)
        emit = jnp.logical_and(stage == n_stages - 1, j >= 0)
        outs = jnp.where(emit, updated, outs)
        return (y, outs), None

    (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(ticks))
    # Replicate the final stage's buffer to every device (others hold zeros).
    return lax.psum(outs, axis_name)
