"""Pipeline parallelism: GPipe and 1F1B schedules over a ``pp`` mesh axis.

The reference has **no** pipeline parallelism (SURVEY.md §2.3: PP absent) —
here the TPU mapping is natural: stages live on consecutive devices along
the ``pp`` axis, activations hop stage→stage with ``ppermute``
(nearest-neighbour ICI), and each schedule is one ``lax.scan`` — a single
compiled SPMD program, no per-microbatch host dispatch.

Two schedules:

- :func:`pipeline_apply` — GPipe forward. ``jax.grad`` through it IS
  pipeline-parallel backprop (every op has a transpose rule); activation
  residuals for ALL ``n_micro`` microbatches are stashed by scan's autodiff,
  so memory grows with the microbatch count.
- :func:`pipeline_1f1b` — explicit one-forward-one-backward schedule
  computing (loss, param grads) in a single scan. Residuals are held in a
  circular buffer of depth ``n_stages`` (the 1F1B in-flight bound): per-stage
  activation memory is O(n_stages), independent of ``n_micro`` — the reason
  real PP training uses 1F1B.

Shape-changing stages (r5, VERDICT r4 #4): the first and last stages may
differ from the trunk — ``first_fn`` (e.g. token embedding: ids → hidden)
runs only on stage 0 and ``last_fn`` (e.g. final-norm+head+loss) only on the
last stage, so a REAL transformer splits embed→blocks→head across the pipe.
The inter-stage stream is the fixed-shape trunk activation; the microbatch
input stream ``xs`` is whatever ``first_fn`` consumes (token ids — a few KB
per microbatch, NOT the replicated hidden-state stream of the r4 design).
"""

from __future__ import annotations

from typing import Callable, Optional


def _identity_first(params, x):
    return x


def _identity_last(params, y, mb):
    return y


def _index_stream(xs, i):
    """Index a pytree of (n_micro, ...) streams at microbatch i."""
    import jax
    from jax import lax

    return jax.tree_util.tree_map(
        lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), xs
    )


def _stream_len(xs) -> int:
    import jax

    return jax.tree_util.tree_leaves(xs)[0].shape[0]


def pipeline_apply(
    stage_fn: Callable,
    local_params,
    xs,
    axis_name: str,
    *,
    first_fn: Optional[Callable] = None,
    last_fn: Optional[Callable] = None,
    act_shape: Optional[tuple] = None,
    act_dtype=None,
    out_shape: Optional[tuple] = None,
    out_dtype=None,
):
    """GPipe forward over the ``axis_name`` mesh axis (inside shard_map).

    stage_fn: (params, act) -> act — the trunk, shape-preserving.
    first_fn: (params, microbatch) -> act — stage 0's input adapter
      (default: identity, microbatch must already be act-shaped).
    last_fn: (params, act, microbatch) -> out — the last stage's output
      adapter (default: identity); receives the SAME microbatch element the
      activation came from (e.g. its loss targets).
    local_params: THIS stage's parameter pytree (stack per-stage params
      outside, shard dim 0 over ``pp``, squeeze before passing; params only
      used by first_fn/last_fn may be present on every stage — unused slots
      are dead code on the others).
    xs: a PYTREE of (n_micro, ...) streams (e.g. {"idx": ids, "tgt":
      targets}); stage 0's first_fn and the last stage's last_fn read it.
    act_shape/act_dtype: trunk activation shape (inferred from xs when
      first_fn is None and xs is a single array).
    out_shape/out_dtype: last_fn output shape (inferred: act).

    Returns (n_micro,) + out_shape outputs, replicated across the axis.
    ``n_micro + n_stages − 1`` ticks (the GPipe bubble).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    first_fn = first_fn or _identity_first
    last_fn = last_fn or _identity_last

    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = _stream_len(xs)
    ticks = n_micro + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    if act_shape is None:
        leaf = jax.tree_util.tree_leaves(xs)[0]
        act_shape, act_dtype = leaf.shape[1:], leaf.dtype
    act0 = jnp.zeros(act_shape, act_dtype)
    mb0 = _index_stream(xs, 0)
    if out_shape is None:
        out_eval = jax.eval_shape(lambda p, a, m: last_fn(p, a, m), local_params, act0, mb0)
        out_shape, out_dtype = out_eval.shape, out_eval.dtype
    outs0 = jnp.zeros((n_micro,) + tuple(out_shape), out_dtype)

    def tick(carry, t):
        act, outs = carry
        # Trunk activations hop one stage down the ring.
        recv = lax.ppermute(act, axis_name, perm)
        # Stage 0 embeds the next microbatch during the fill/steady phase.
        mb = _index_stream(xs, jnp.minimum(t, n_micro - 1))
        fed = first_fn(local_params, mb)
        feed = jnp.where(t < n_micro, fed, jnp.zeros_like(act0))
        x_in = jnp.where(stage == 0, feed, recv)
        y = stage_fn(local_params, x_in)
        # The final stage emits microbatch t − (n_stages − 1) once the
        # pipe is full; earlier ticks and other stages write nothing.
        j = t - (n_stages - 1)
        mb_out = _index_stream(xs, jnp.clip(j, 0, n_micro - 1))
        o = last_fn(local_params, y, mb_out)
        updated = lax.dynamic_update_index_in_dim(outs, o, jnp.maximum(j, 0), 0)
        emit = jnp.logical_and(stage == n_stages - 1, j >= 0)
        outs = jnp.where(emit, updated, outs)
        return (y, outs), None

    (_, outs), _ = lax.scan(tick, (act0, outs0), jnp.arange(ticks))
    # Replicate the final stage's buffer to every device (others hold zeros).
    return lax.psum(outs, axis_name)


def pipeline_1f1b(
    stage_fn: Callable,
    local_params,
    xs,
    axis_name: str,
    *,
    first_fn: Optional[Callable] = None,
    last_fn: Optional[Callable] = None,
    act_shape: Optional[tuple] = None,
    act_dtype=None,
):
    """1F1B pipeline training step: ``(mean loss, param grads)`` in one scan.

    ``last_fn(params, act, microbatch) -> scalar loss`` per microbatch; the
    cotangent seeded into the backward is ``1/n_micro`` (mean over
    microbatches). Residuals live in a depth-``n_stages`` circular buffer —
    the 1F1B in-flight bound — so per-stage activation memory is
    O(n_stages · |act|), independent of ``n_micro`` (GPipe-via-autodiff
    stashes all ``n_micro``).

    Schedule (classic non-interleaved 1F1B, expressed as a uniform SPMD
    tick): stage ``s`` runs forward for microbatch ``f`` at tick
    ``s + 2·f`` and backward for microbatch ``b`` at tick
    ``2·n_stages − 2 − s + 2·b + 1`` — between warmup and drain each stage
    alternates one-forward/one-backward. Total ``2·(n_micro + n_stages − 1)``
    ticks. Forward activations hop down the ring on even phases, cotangents
    hop back up on odd phases.

    Returns ``(loss_mean, grads)`` with ``grads`` matching ``local_params``
    (each stage's grads for ITS OWN slice; first/last-stage-only params get
    nonzero grads only where used — combine across stages outside if params
    are stacked).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    first_fn = first_fn or _identity_first
    if last_fn is None:
        raise ValueError(
            "pipeline_1f1b requires last_fn: (params, act, microbatch) -> "
            "scalar loss — the schedule seeds its backward from it"
        )

    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = _stream_len(xs)
    down = [(i, i + 1) for i in range(n_stages - 1)]
    up = [(i + 1, i) for i in range(n_stages - 1)]

    if act_shape is None:
        leaf = jax.tree_util.tree_leaves(xs)[0]
        act_shape, act_dtype = leaf.shape[1:], leaf.dtype
    act0 = jnp.zeros(act_shape, act_dtype)

    def fwd_one(mb, act_in):
        """One stage-forward of one microbatch. Residual = the stage's INPUT
        (microbatch for stage 0's first_fn path, trunk activation elsewhere)
        — the backward recomputes the vjp from it (input-stashing 1F1B; the
        per-stage recompute is one stage_fn forward, the standard
        memory/time trade)."""
        x_in = jnp.where(stage == 0, first_fn(local_params, mb), act_in)
        return stage_fn(local_params, x_in)

    def bwd_one(mb, act_in, ct_out):
        """vjp of this stage's step for one microbatch: cotangent w.r.t. the
        incoming trunk activation + this stage's param grads. The last stage
        seeds from the loss instead of a received cotangent."""
        def full(params, act):
            x_in = jnp.where(stage == 0, first_fn(params, mb), act)
            y = stage_fn(params, x_in)
            loss = last_fn(params, y, mb)
            is_last = stage == n_stages - 1
            # Non-last stages: pull back ct_out through y. Last stage:
            # pull back the mean-loss seed through the scalar loss.
            return jnp.where(
                is_last,
                (loss / n_micro).astype(jnp.float32),
                jnp.sum(y.astype(jnp.float32) * ct_out.astype(jnp.float32)),
            )

        val, (g_params, g_act) = jax.value_and_grad(full, argnums=(0, 1))(local_params, act_in)
        # val IS loss/n_micro on the last stage (the seed); elsewhere it is
        # the pullback inner product — the caller masks by stage.
        return val, g_act, g_params

    ticks = 2 * (n_micro + n_stages - 1)

    saved_mb0 = jax.tree_util.tree_map(
        lambda a: jnp.zeros((n_stages,) + a.shape[1:], a.dtype), xs
    )
    saved_act0 = jnp.zeros((n_stages,) + tuple(act_shape), act_dtype)
    # f32 grad accumulators: n_micro bf16 additions would lose low bits.
    g0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), local_params
    )
    loss0 = jnp.zeros((), jnp.float32)

    def tick(carry, t):
        act_fwd, ct_bwd, saved_mb, saved_act, grads, loss_acc = carry

        # ---- forward phase: stage s runs fwd(f) at tick s + 2f --------
        f_idx = (t - stage) // 2
        fwd_live = jnp.logical_and((t - stage) % 2 == 0,
                                   jnp.logical_and(f_idx >= 0, f_idx < n_micro))

        recv_act = lax.ppermute(act_fwd, axis_name, down)
        mb = _index_stream(xs, jnp.clip(f_idx, 0, n_micro - 1))
        act_in = jnp.where(stage == 0, jnp.zeros_like(act0), recv_act)
        y = fwd_one(mb, act_in)
        slot = jnp.clip(f_idx, 0, n_micro - 1) % n_stages
        saved_mb = jax.tree_util.tree_map(
            lambda buf, el: jnp.where(
                fwd_live, lax.dynamic_update_index_in_dim(buf, el, slot, 0), buf
            ),
            saved_mb, mb,
        )
        saved_act = jnp.where(
            fwd_live, lax.dynamic_update_index_in_dim(saved_act, act_in, slot, 0), saved_act
        )
        act_out = jnp.where(fwd_live, y, jnp.zeros_like(act0))

        # ---- backward phase: stage s runs bwd(b) at tick
        #      2·(n_stages−1) − s + 2b + 1 (opposite parity to fwd) -----
        b_off = t - (2 * (n_stages - 1) - stage) - 1
        b_idx = b_off // 2
        bwd_live = jnp.logical_and(
            b_off % 2 == 0, jnp.logical_and(b_idx >= 0, b_idx < n_micro)
        )
        recv_ct = lax.ppermute(ct_bwd, axis_name, up)
        bslot = jnp.clip(b_idx, 0, n_micro - 1) % n_stages
        r_mb = _index_stream(saved_mb, bslot)
        r_act = lax.dynamic_index_in_dim(saved_act, bslot, 0, keepdims=False)
        val, g_act, g_params = bwd_one(r_mb, r_act, recv_ct)
        ct_out = jnp.where(bwd_live, g_act, jnp.zeros_like(act0))
        grads = jax.tree_util.tree_map(
            lambda g, gp: g + jnp.where(bwd_live, gp.astype(jnp.float32), 0.0),
            grads, g_params,
        )
        # Loss tracking rides the backward's value_and_grad — no extra
        # last_fn forward per tick: on the last stage val = loss/n_micro
        # for the microbatch just backpropagated.
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(bwd_live, stage == n_stages - 1), val, 0.0
        )

        return (act_out, ct_out, saved_mb, saved_act, grads, loss_acc), None

    init = (act0, jnp.zeros_like(act0), saved_mb0, saved_act0, g0, loss0)
    (_, _, _, _, grads, loss_acc), _ = lax.scan(tick, init, jnp.arange(ticks))
    loss = lax.psum(loss_acc, axis_name)  # loss_acc already carries 1/n_micro
    return loss, grads
