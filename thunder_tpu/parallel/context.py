"""Sequence/context parallelism: ring attention and Ulysses (all-to-all).

The reference has **no** long-context support (SURVEY.md §5: no ring
attention, no sequence parallelism anywhere in the tree) — these are
first-class here because the TPU torus makes them natural:

- **Ring attention** (`ring_attention`): K/V shards rotate around the
  ``sp`` ring via `ppermute` while each device accumulates online-softmax
  partial results for its local Q block. Peak memory per device is
  O(S_local²) scores instead of O(S²); ICI neighbour hops overlap with the
  per-block matmuls under XLA's scheduler. Written in pure differentiable
  jax (ppermute has a transpose rule), so jax.grad/our VJP-of-executor
  path both work.
- **Ulysses** (`ulysses_attention`): all-to-all reshards (seq-sharded →
  head-sharded), runs dense/flash attention on full sequences per head
  group, and reshards back — two all-to-alls per attention instead of a
  ring of p2p steps; better when heads ≥ sp and ICI all-to-all bandwidth
  is plentiful.

Both run inside ``shard_map`` over a mesh ``sp`` axis (see
tests/_dist_worker.py scenarios for the 8-device CPU-mesh proofs).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional


def _block_attn(q, k, v, *, scale, q_offset, k_offset, causal):
    """One (S_q_local, S_k_local) attention block with global-position causal
    masking. Returns (o_unnormalized, m, l) for online-softmax merging."""
    import jax
    import jax.numpy as jnp

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        Sq, Sk = q.shape[-2], k.shape[-2]
        qpos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        kpos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)  # (B,H,Sq,1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)  # fully-masked rows
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vf)
    return o, m_safe, l


def ring_attention(q, k, v, axis_name: str, *, causal: bool = True, scale: Optional[float] = None):
    """Causal attention with sequence sharded over the mesh axis
    ``axis_name``. q/k/v: (B, H, S_local, D) per device; output matches q.

    K/V rotate one ring hop per step; each device merges the incoming
    block's contribution with the running (out, max, denom) accumulator —
    the blockwise/online-softmax formulation of flash attention lifted to
    the device ring.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    S_local = q.shape[-2]
    D = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(D)

    q_offset = my * S_local
    perm = None  # built lazily from the static axis size

    o_acc = jnp.zeros(q.shape[:-1] + (D,), dtype=jnp.float32)
    m_acc = jnp.full(q.shape[:-1] + (1,), -jnp.inf, dtype=jnp.float32)
    l_acc = jnp.zeros(q.shape[:-1] + (1,), dtype=jnp.float32)

    k_cur, v_cur = k, v
    # The axis size is static under shard_map, so a Python loop unrolls into
    # n pipeline stages XLA can overlap (ppermute_i+1 with block-matmul_i).
    n_static = int(n) if not hasattr(n, "aval") else None
    if n_static is None:
        raise ValueError("ring_attention requires a static mesh axis size")

    for step in range(n_static):
        src = (my - step) % n  # which global block k_cur/v_cur hold
        k_offset = src * S_local
        o, m, l = _block_attn(q, k_cur, v_cur, scale=scale, q_offset=q_offset,
                              k_offset=k_offset, causal=causal)
        # online-softmax merge
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)  # rescale old accumulator
        beta = jnp.exp(m - m_new)  # rescale new block
        o_acc = o_acc * alpha + o * beta
        l_acc = l_acc * alpha + l * beta
        m_acc = m_new
        if step + 1 < n_static:
            ring = [(i, (i + 1) % n_static) for i in range(n_static)]
            k_cur = lax.ppermute(k_cur, axis_name, ring)
            v_cur = lax.ppermute(v_cur, axis_name, ring)

    out = o_acc / jnp.maximum(l_acc, 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = True, scale: Optional[float] = None):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all from
    seq-sharded (B, H, S/p, D) to head-sharded (B, H/p, S, D), dense/flash
    attention over the full sequence, then all-to-all back. Requires
    H % axis_size == 0."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = int(lax.psum(1, axis_name)) if not hasattr(lax.psum(1, axis_name), "aval") else None
    # axis size is static inside shard_map
    n = n if n is not None else 1
    B, H, S_local, D = q.shape
    assert H % n == 0, f"heads {H} must divide sp axis {n}"

    def to_head_sharded(x):
        # (B, H, S/p, D) → (B, H/p, S, D). With tiled=False, all_to_all
        # removes the split axis and inserts a source-device axis at the
        # concat position — the device axis IS the seq-block index.
        x = x.reshape(B, n, H // n, S_local, D)
        x = lax.all_to_all(x, axis_name, split_axis=1, concat_axis=3, tiled=False)
        # (B, H//n, S_local, n, D); seq order must be block-major:
        x = jnp.swapaxes(x, 2, 3)  # (B, H//n, n, S_local, D)
        return x.reshape(B, H // n, n * S_local, D)

    def to_seq_sharded(x):
        # (B, H/p, S, D) → (B, H, S/p, D); inverse of the above.
        x = x.reshape(B, H // n, n, S_local, D)
        x = lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
        # (B, n, H//n, S_local, D); head order is group-major:
        return x.reshape(B, H, S_local, D)

    qh, kh, vh = to_head_sharded(q), to_head_sharded(k), to_head_sharded(v)
    o, _, l = _block_attn(qh, kh, vh, scale=scale if scale is not None else 1.0 / math.sqrt(D),
                          q_offset=0, k_offset=0, causal=causal)
    o = (o / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return to_seq_sharded(o)
