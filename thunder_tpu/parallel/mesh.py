"""Device meshes for SPMD execution.

Axes follow the scaling-book convention: ``dp`` (pure data parallel,
typically over DCN between slices), ``pp`` (pipeline stages — slowest links,
point-to-point only), ``fsdp`` (data parallel with sharded params/grads/
optimizer — ZeRO — over ICI), ``ep`` (expert parallel, all-to-all heavy),
``tp`` (tensor/model parallel over ICI), ``sp`` (sequence/context parallel).
A mesh only has the axes you give it; every sharding helper treats absent
axes as size-1.

Reference parity: takes the seat of torch.distributed process groups
(reference: thunder/distributed/__init__.py:193,348 init_process_group) —
here a mesh is data, not processes: `jax.distributed.initialize` + the same
code runs on every host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")

# The cross-slice federation axis (ISSUE 18): a federated mesh prepends it
# to AXIS_ORDER, so slices are the slowest-varying device groups — exactly
# the boundary DCN links sit on. In-slice axes keep their ICI ordering.
DCN_AXIS = "dcn"


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    def axis_sizes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    @classmethod
    def from_mesh(cls, mesh) -> "MeshConfig":
        """Recover the config from a live ``jax.sharding.Mesh`` (axes the
        mesh doesn't carry default to 1)."""
        return cls(**{a: int(n) for a, n in axis_sizes(mesh).items()
                      if a in AXIS_ORDER})


def axis_sizes(mesh) -> dict[str, int]:
    """``{axis: size}`` of a live mesh — the mesh-shape record the elastic
    checkpoint metadata stores (``CheckpointManager.save(mesh=...)``) and
    the resume path compares against the surviving mesh
    (``resilience/elastic.py``)."""
    return {str(a): int(n) for a, n in zip(mesh.axis_names, mesh.devices.shape)}


def make_mesh(config: MeshConfig | dict | None = None, *, devices: Optional[Sequence] = None, **axes):
    """Build a `jax.sharding.Mesh` with the given axis sizes.

    Axis order is fixed (dp, pp, fsdp, ep, sp, tp) — outer axes change
    slowest, so dp/pp land across DCN / slice boundaries and tp across
    adjacent ICI neighbours, matching how `jax.devices()` orders a slice.
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**{k: int(v) for k, v in axes.items()})
    elif isinstance(config, dict):
        config = MeshConfig(**config)

    devs = list(devices) if devices is not None else jax.devices()
    n = config.n_devices
    if len(devs) < n:
        raise ValueError(f"Mesh needs {n} devices, only {len(devs)} available")
    shape = tuple(config.axis_sizes()[a] for a in AXIS_ORDER)
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


# =============================================================================
# Federated (slice-granular) meshes — ISSUE 18
# =============================================================================


@dataclass(frozen=True)
class SliceTopology:
    """Static description of one federated mesh: which contiguous device
    block each emulated ICI slice owns. Slice i holds devices
    ``[i*devices_per_slice, (i+1)*devices_per_slice)`` of the flat device
    list — contiguous so in-slice collectives stay on "ICI" neighbours and
    only the leading :data:`DCN_AXIS` hops cross the slice boundary."""

    n_slices: int
    devices_per_slice: int
    per_slice: MeshConfig

    @property
    def n_devices(self) -> int:
        return self.n_slices * self.devices_per_slice

    def slice_of_device(self, flat_index: int) -> int:
        """Slice owning flat device index ``flat_index``."""
        return int(flat_index) // self.devices_per_slice

    def device_indices(self, slice_id: int) -> range:
        """Flat device indices of ``slice_id``'s block."""
        lo = int(slice_id) * self.devices_per_slice
        return range(lo, lo + self.devices_per_slice)


def make_federated_mesh(
    n_slices: int,
    config: MeshConfig | dict | None = None,
    *,
    devices: Optional[Sequence] = None,
    **axes,
):
    """Build a hierarchical ``jax.sharding.Mesh`` federating ``n_slices``
    emulated ICI slices over a leading :data:`DCN_AXIS`.

    ``config``/``axes`` describe ONE slice (the in-slice ICI mesh); the
    returned mesh has axes ``("dcn",) + AXIS_ORDER`` and shape
    ``(n_slices, dp, pp, fsdp, ep, sp, tp)``. Per-slice device blocks are
    contiguous in the flat device list, so the "dcn" axis is the only axis
    whose collectives cross a slice boundary — which is what lets
    hierarchical lowering (``dist_prims.hier_all_reduce``) and the cost
    model's DCN bandwidth class price in-slice vs cross-slice traffic
    separately. Returns ``(mesh, SliceTopology)``."""
    import jax
    from jax.sharding import Mesh

    if n_slices < 1:
        raise ValueError(f"need at least 1 slice, got {n_slices}")
    if config is None:
        config = MeshConfig(**{k: int(v) for k, v in axes.items()})
    elif isinstance(config, dict):
        config = MeshConfig(**config)

    devs = list(devices) if devices is not None else jax.devices()
    per_slice = config.n_devices
    n = n_slices * per_slice
    if len(devs) < n:
        raise ValueError(
            f"Federated mesh needs {n} devices ({n_slices} slices × "
            f"{per_slice}), only {len(devs)} available"
        )
    shape = (n_slices,) + tuple(config.axis_sizes()[a] for a in AXIS_ORDER)
    arr = np.array(devs[:n]).reshape(shape)
    topo = SliceTopology(n_slices=n_slices, devices_per_slice=per_slice,
                         per_slice=config)
    return Mesh(arr, (DCN_AXIS,) + AXIS_ORDER), topo


def is_federated(mesh) -> bool:
    """True when ``mesh`` carries the cross-slice :data:`DCN_AXIS`."""
    return DCN_AXIS in tuple(getattr(mesh, "axis_names", ()) or ())


def slice_axis_size(mesh) -> int:
    """Number of slices a federated mesh spans (1 for a plain ICI mesh)."""
    return axis_sizes(mesh).get(DCN_AXIS, 1)
