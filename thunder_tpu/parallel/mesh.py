"""Device meshes for SPMD execution.

Axes follow the scaling-book convention: ``dp`` (pure data parallel,
typically over DCN between slices), ``pp`` (pipeline stages — slowest links,
point-to-point only), ``fsdp`` (data parallel with sharded params/grads/
optimizer — ZeRO — over ICI), ``ep`` (expert parallel, all-to-all heavy),
``tp`` (tensor/model parallel over ICI), ``sp`` (sequence/context parallel).
A mesh only has the axes you give it; every sharding helper treats absent
axes as size-1.

Reference parity: takes the seat of torch.distributed process groups
(reference: thunder/distributed/__init__.py:193,348 init_process_group) —
here a mesh is data, not processes: `jax.distributed.initialize` + the same
code runs on every host.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np


AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    def axis_sizes(self) -> dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "fsdp": self.fsdp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}

    @classmethod
    def from_mesh(cls, mesh) -> "MeshConfig":
        """Recover the config from a live ``jax.sharding.Mesh`` (axes the
        mesh doesn't carry default to 1)."""
        return cls(**{a: int(n) for a, n in axis_sizes(mesh).items()
                      if a in AXIS_ORDER})


def axis_sizes(mesh) -> dict[str, int]:
    """``{axis: size}`` of a live mesh — the mesh-shape record the elastic
    checkpoint metadata stores (``CheckpointManager.save(mesh=...)``) and
    the resume path compares against the surviving mesh
    (``resilience/elastic.py``)."""
    return {str(a): int(n) for a, n in zip(mesh.axis_names, mesh.devices.shape)}


def make_mesh(config: MeshConfig | dict | None = None, *, devices: Optional[Sequence] = None, **axes):
    """Build a `jax.sharding.Mesh` with the given axis sizes.

    Axis order is fixed (dp, pp, fsdp, ep, sp, tp) — outer axes change
    slowest, so dp/pp land across DCN / slice boundaries and tp across
    adjacent ICI neighbours, matching how `jax.devices()` orders a slice.
    """
    import jax
    from jax.sharding import Mesh

    if config is None:
        config = MeshConfig(**{k: int(v) for k, v in axes.items()})
    elif isinstance(config, dict):
        config = MeshConfig(**config)

    devs = list(devices) if devices is not None else jax.devices()
    n = config.n_devices
    if len(devs) < n:
        raise ValueError(f"Mesh needs {n} devices, only {len(devs)} available")
    shape = tuple(config.axis_sizes()[a] for a in AXIS_ORDER)
    arr = np.array(devs[:n]).reshape(shape)
    return Mesh(arr, AXIS_ORDER)
