"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

The reference has **no** MoE or expert parallelism (SURVEY.md §2.3: EP
absent) — this is first-class here because the TPU ICI all-to-all makes the
canonical dispatch pattern natural:

- Experts are sharded over ``ep``: each device owns ``E / ep`` experts'
  weights (the expert-parallel memory win).
- Tokens are routed top-k by a learned router, packed into per-expert
  capacity slots via one-hot dispatch einsums (dense, MXU-friendly — no
  data-dependent shapes, the XLA-compatible form of token dropping), sent
  to the owning devices with ONE ``all_to_all``, transformed by the local
  experts as a batched einsum, and returned with the reverse ``all_to_all``;
  the combine einsum applies the router weights.
- Written in pure differentiable jax (all_to_all has a transpose rule), so
  ``jax.grad`` through the routed computation — including the router —
  works; run inside ``shard_map`` over the ``ep`` axis.

With ``capacity_factor`` high enough that no token is dropped, the result
is exactly the dense computation ``Σ_k p_k · expert_{i_k}(x)`` — the
8-device CPU-mesh test asserts that equivalence and gradient parity.
"""

from __future__ import annotations

from typing import Optional


def moe_mlp(
    x,
    router_w,
    w1,
    w2,
    axis_name: str,
    *,
    top_k: int = 2,
    capacity: Optional[int] = None,
    activation=None,
):
    """Expert-parallel MoE MLP for one device's token shard.

    Args (local, inside shard_map over ``axis_name``):
      x: (n, d) local tokens.
      router_w: (d, E) router weights, replicated. E = total experts.
      w1: (E_local, d, h) this device's expert up-projections.
      w2: (E_local, h, d) this device's expert down-projections.
      top_k: experts per token.
      capacity: per-(source device, expert) slot count C. Default n
        (no token ever dropped — exact dense equivalence); production
        configs use ~ top_k·n/E · capacity_factor.

    Returns (n, d) combined expert outputs (router-weighted).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, d = x.shape
    e_local = w1.shape[0]
    ep = lax.psum(1, axis_name)
    E = e_local * ep
    C = int(capacity) if capacity is not None else n
    act = activation if activation is not None else jax.nn.gelu

    xf = x.astype(jnp.float32)
    logits = xf @ router_w.astype(jnp.float32)  # (n, E)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)  # (n, k)

    # Dense dispatch bookkeeping (Shazeer-style): slot position of each
    # (token, choice) within its expert's capacity, dropped when over C.
    choice_mask = jax.nn.one_hot(top_i, E, dtype=jnp.float32)  # (n, k, E)
    flat_mask = choice_mask.reshape(n * top_k, E)
    pos = jnp.cumsum(flat_mask, axis=0) - flat_mask  # slot index per (n·k, E)
    pos = (pos * flat_mask).reshape(n, top_k, E)
    keep = (pos < C).astype(jnp.float32) * choice_mask
    slot_oh = jax.nn.one_hot(pos.astype(jnp.int32), C, dtype=jnp.float32)  # (n,k,E,C)
    dispatch = (slot_oh * keep[..., None]).sum(axis=1)  # (n, E, C) ∈ {0,1}
    combine = (slot_oh * (keep * top_p[..., None])[..., None]).sum(axis=1)  # (n, E, C)

    # Pack and ship: device m's sent[g·e_local + l] holds its tokens bound
    # for device g's local expert l. Tiled all_to_all splits dim 0 into ep
    # groups and concatenates what each device receives along dim 1:
    # recv[l, m·C + c] = device m's capacity slot c for my local expert l.
    sent = jnp.einsum("nd,nec->ecd", xf, dispatch)  # (E, C, d)
    recv = lax.all_to_all(sent, axis_name, split_axis=0, concat_axis=1, tiled=True)
    # recv: (e_local, ep·C, d)

    # Local experts as one batched einsum pair (MXU).
    h = act(jnp.einsum("ecd,edh->ech", recv, w1.astype(jnp.float32)))
    y = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32))  # (e_local, ep·C, d)

    # Return trip (the exact transpose shuffle) + combine.
    back = lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0, tiled=True)
    # back: (E, C, d) — back[g·e_local + l, c] = global expert g·e_local+l's
    # output for my capacity slot c.
    out = jnp.einsum("ecd,nec->nd", back, combine)
    return out.astype(x.dtype)


def moe_mlp_dense_reference(x, router_w, w1_full, w2_full, *, top_k: int = 2, activation=None):
    """Oracle: per-token dense Σ_k p_k · expert_{i_k}(x) with the FULL
    (unsharded) expert weights. Exactly what moe_mlp computes when no token
    is dropped."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    act = activation if activation is not None else jax.nn.gelu
    xf = x.astype(jnp.float32)
    probs = jax.nn.softmax(xf @ router_w.astype(jnp.float32), axis=-1)
    top_p, top_i = lax.top_k(probs, top_k)

    # Compute every expert on every token (dense), then select.
    h = act(jnp.einsum("nd,edh->neh", xf, w1_full.astype(jnp.float32)))
    all_out = jnp.einsum("neh,ehd->ned", h, w2_full.astype(jnp.float32))  # (n, E, d)
    sel = jnp.take_along_axis(all_out, top_i[..., None], axis=1)  # (n, k, d)
    return (sel * top_p[..., None]).sum(axis=1).astype(x.dtype)
