"""TPU-native parallelism: device meshes, sharding plans, sharded train steps.

This package is the performance path that takes the seat of the reference's
`thunder/distributed/` NCCL machinery (reference: distributed/__init__.py
`ddp:88` / `fsdp:303`, bucketing, `sort_waits` comm scheduling): on TPU the
mesh + PartitionSpec annotations let XLA's SPMD partitioner insert and
schedule collectives over ICI/DCN, replacing hand-written bucketing and wait
sorting (SURVEY.md §5 "Distributed communication backend").

Explicit trace-level collectives (the reference's distributed/prims.py
surface) live in ``thunder_tpu.distributed``.
"""

from thunder_tpu.parallel.mesh import (  # noqa: F401
    MeshConfig,
    SliceTopology,
    make_federated_mesh,
    make_mesh,
)
from thunder_tpu.parallel.sharding import (  # noqa: F401
    data_spec,
    gpt_param_specs,
    named_shardings,
    shard_pytree,
)
from thunder_tpu.parallel.train import adamw_init, adamw_update, build_train_step  # noqa: F401
from thunder_tpu.parallel.moe import moe_mlp, moe_mlp_dense_reference  # noqa: F401
from thunder_tpu.parallel.pipeline import pipeline_apply  # noqa: F401
