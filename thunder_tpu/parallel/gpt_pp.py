"""A real GPT split embed→blocks→head across a ``pp`` mesh axis.

VERDICT r4 #4: the r4 pipeline demonstrator replicated the activation
stream on every stage and required shape-preserving stages. Here the
models/gpt.py transformer is genuinely pipelined:

- stage 0 embeds token ids (``first_fn``); the LAST stage applies the final
  norm + lm_head + cross-entropy (``last_fn``) — shape-changing first/last
  stages, with the fixed-shape trunk activation (mb, T, n_embd) as the only
  inter-stage traffic (nearest-neighbour ppermute over ICI);
- each stage owns ``n_layer / n_stages`` consecutive blocks (its trunk);
- the microbatch stream is TOKEN IDS + targets — a few KB per microbatch —
  not hidden states;
- both schedules work: GPipe (:func:`thunder_tpu.parallel.pipeline
  .pipeline_apply` under ``jax.grad``) and memory-bounded 1F1B
  (:func:`pipeline_1f1b`).

The per-stage compute is built from the framework's own trace pipeline:
the ttorch model functions are traced once (trace_program → claiming →
``python_callable``) into pure-jax callables that lax.scan/ppermute then
schedule — the same staging path the single-device trainer uses.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from thunder_tpu.models.gpt import GPTConfig


def _staged(fn, example_args, executors: Optional[Sequence[str]]):
    """Trace a ttorch function on example inputs → pure-jax flat callable.

    The callable's positional args are the TENSOR leaves of example_args in
    pytree order (jax flatten: dict keys sorted) — callers must pass live
    values flattened the same way."""
    from thunder_tpu.api import trace_program
    from thunder_tpu.core.pytree import tree_flatten
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.common import cse, dce

    _, comp = trace_program(fn, example_args, {})
    call = transform_for_execution(
        cse(dce(comp)), resolve_executors(list(executors) if executors else None)
    ).python_callable()

    def flat_call(*live_args):
        flat, _ = tree_flatten((tuple(live_args), {}))
        import jax

        tensors = [x for x in flat if isinstance(x, (jax.Array, np.ndarray)) or hasattr(x, "dtype")]
        return call(*tensors)

    return flat_call


def split_params_for_pp(params: dict, n_stages: int) -> dict:
    """Stack per-stage parameters for a ``P("pp", ...)`` sharding.

    Returns {"blocks": stacked-per-stage block pytree with a leading
    (n_stages,) axis, "wte"/"ln_f"/"lm_head_w": replicated}. Stage s's
    local slice after shard_map squeezing is its own ``n_layer/n_stages``
    blocks plus the (replicated) embed/head weights its adapters may use.
    """
    import jax.numpy as jnp

    blocks = params["blocks"]
    n_layer = len(blocks)
    assert n_layer % n_stages == 0, (n_layer, n_stages)
    per = n_layer // n_stages
    import jax

    stage_blocks = [blocks[s * per:(s + 1) * per] for s in range(n_stages)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_blocks)
    return {
        "blocks": stacked,  # leaves: (n_stages, ...) — shard dim 0 over pp
        "wte": params["wte"],
        "ln_f": params["ln_f"],
        "lm_head_w": params["lm_head_w"],
    }


def merge_pp_grads(grads: dict, n_stages: int, n_layer: int) -> dict:
    """Inverse of split_params_for_pp for gradient pytrees: unstack the
    per-stage block grads back into the flat ``blocks`` list."""
    import jax

    per = n_layer // n_stages
    blocks = []
    for s in range(n_stages):
        stage = jax.tree_util.tree_map(lambda x: x[s], grads["blocks"])
        blocks.extend(stage)
    return {
        "wte": grads["wte"],
        "blocks": blocks,
        "ln_f": grads["ln_f"],
        "lm_head_w": grads["lm_head_w"],
    }


def build_gpt_pp_fns(config: GPTConfig, n_stages: int, mb: int, T: int,
                     *, executors: Optional[Sequence[str]] = ("jax",),
                     dtype=None):
    """(first_fn, stage_fn, last_fn) for the pipeline schedules.

    first_fn(params, stream) embeds stream["idx"]; stage_fn applies the
    stage's blocks; last_fn(params, act, stream) computes the mean
    cross-entropy of the microbatch against stream["tgt"]."""
    from thunder_tpu.core import dtypes as _dt
    from thunder_tpu.models import gpt as m

    per = config.n_layer // n_stages
    # Normalize any dtype-like (framework dtype, jax/np dtype) so callers can
    # forward the live params' dtype directly (ADVICE r5 #1): the staging
    # examples must match the real values or the trunk bakes wrong-precision
    # rope constants and executors claim on wrong dtype metadata.
    fdt = _dt.to_dtype(dtype, true_dtype=True) if dtype is not None else _dt.bfloat16
    jdt = _dt.to_jax_dtype(fdt)

    ex_idx = np.zeros((mb, T), np.int32)
    ex_params = m.init_params(config, dtype=fdt, seed=0)
    ex_x = np.zeros((mb, T, config.n_embd), jdt)
    ex_blocks = ex_params["blocks"][:per]

    import thunder_tpu.torch as ttorch

    embed_call = _staged(
        lambda wte, idx: ttorch.embedding(idx, wte), (ex_params["wte"], ex_idx), executors
    )

    def trunk(blocks, x):
        cos, sin = m._rope_cache(T, config, device=x.device, dtype=x.dtype)
        for p in blocks:
            x = m._block(x, p, cos, sin, config)
        return x

    trunk_call = _staged(trunk, (ex_blocks, ex_x), executors)

    def head(ln_f, head_w, x, tgt):
        x = m._norm(x, ln_f, config)
        logits = ttorch.linear(x, head_w)
        B, TT, V = logits.shape
        return ttorch.cross_entropy(
            ttorch.reshape(logits.float(), (B * TT, V)), ttorch.reshape(tgt, (B * TT,))
        )

    head_call = _staged(
        head, (ex_params["ln_f"], ex_params["lm_head_w"], ex_x, ex_idx), executors
    )

    def first_fn(params, stream):
        return embed_call(params["wte"], stream["idx"])

    def stage_fn(params, x):
        return trunk_call(params["blocks"], x)

    def last_fn(params, y, stream):
        return head_call(params["ln_f"], params["lm_head_w"], y, stream["tgt"])

    return first_fn, stage_fn, last_fn


def gpt_pp_loss_and_grads(config: GPTConfig, params: dict, idx, tgt, mesh,
                          *, n_micro: int, schedule: str = "1f1b",
                          executors: Optional[Sequence[str]] = ("jax",)):
    """End-to-end pipelined (loss, grads) for a models/gpt.py GPT.

    idx/tgt: (B, T) int32 with B divisible by n_micro. Splits the batch
    into microbatches, splits the blocks over the mesh's ``pp`` axis, and
    runs the requested schedule. Returns (loss, grads-with-flat-"blocks").
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax
        from jax.shard_map import shard_map

    n_stages = mesh.shape["pp"]
    B, T = idx.shape
    mb = B // n_micro
    # Stage on the LIVE params' dtype: an f32 model staged on the bf16
    # default would bake bf16 rope cos/sin constants inside an f32 trunk.
    params_dtype = jax.tree_util.tree_leaves(params)[0].dtype
    first_fn, stage_fn, last_fn = build_gpt_pp_fns(
        config, n_stages, mb, T, executors=executors, dtype=params_dtype
    )
    stacked = split_params_for_pp(params, n_stages)
    streams = {
        "idx": jnp.asarray(idx).reshape(n_micro, mb, T),
        "tgt": jnp.asarray(tgt).reshape(n_micro, mb, T),
    }

    from thunder_tpu.parallel.pipeline import pipeline_1f1b, pipeline_apply

    act_shape = (mb, T, config.n_embd)
    act_dtype = jax.tree_util.tree_leaves(params)[0].dtype

    def squeeze_local(stacked_local) -> dict:
        # shard_map hands each stage a (1, ...)-leading block slice; drop it.
        # stacked["blocks"] keeps the list-of-dicts structure, so the result
        # is directly this stage's list of block param dicts.
        local = dict(stacked_local)
        local["blocks"] = jax.tree_util.tree_map(lambda x: x[0], stacked_local["blocks"])
        return local

    def local_1f1b(stacked_local, streams):
        from jax import lax

        loss, grads = pipeline_1f1b(
            stage_fn, squeeze_local(stacked_local), streams, "pp",
            first_fn=first_fn, last_fn=last_fn,
            act_shape=act_shape, act_dtype=act_dtype,
        )
        # Block grads go out per-stage (P("pp") — re-add the stage axis);
        # replicated-param grads psum (each stage contributed only its use:
        # wte on stage 0, head on the last, zeros elsewhere).
        return loss, {
            "blocks": jax.tree_util.tree_map(lambda g: g[None], grads["blocks"]),
            "wte": lax.psum(grads["wte"], "pp"),
            "ln_f": jax.tree_util.tree_map(lambda g: lax.psum(g, "pp"), grads["ln_f"]),
            "lm_head_w": lax.psum(grads["lm_head_w"], "pp"),
        }

    def local_gpipe_losses(stacked_local, streams):
        return pipeline_apply(
            stage_fn, squeeze_local(stacked_local), streams, "pp",
            first_fn=first_fn, last_fn=last_fn,
            act_shape=act_shape, act_dtype=act_dtype,
            out_shape=(), out_dtype=jnp.float32,
        )

    block_in_spec = jax.tree_util.tree_map(lambda _: P("pp"), stacked["blocks"])
    stream_spec = {"idx": P(), "tgt": P()}
    in_specs = ({"blocks": block_in_spec, "wte": P(),
                 "ln_f": jax.tree_util.tree_map(lambda _: P(), stacked["ln_f"]),
                 "lm_head_w": P()}, stream_spec)

    if schedule == "1f1b":
        out_specs = (P(), {"blocks": block_in_spec, "wte": P(),
                           "ln_f": jax.tree_util.tree_map(lambda _: P(), stacked["ln_f"]),
                           "lm_head_w": P()})
        loss, g = jax.jit(shard_map(
            local_1f1b, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        ))(stacked, streams)
        grads = merge_pp_grads(g, n_stages, config.n_layer)
        return loss, grads

    # GPipe: per-microbatch losses via pipeline_apply; grads via jax.grad.
    def mean_loss(stacked, streams):
        losses = shard_map(
            local_gpipe_losses, mesh=mesh, in_specs=in_specs, out_specs=P(),
            check_rep=False,
        )(stacked, streams)
        return jnp.mean(losses)

    loss, g = jax.jit(jax.value_and_grad(mean_loss))(stacked, streams)
    grads = merge_pp_grads(g, n_stages, config.n_layer)
    return loss, grads


