"""Sharding plans for the GPT param pytree.

Reference parity: thunder/distributed/__init__.py `fsdp:303` dim-0
per-parameter sharding (`_shard_param:406`) and `ddp:88` replication —
re-expressed as PartitionSpecs so XLA's SPMD partitioner takes the seats of
the all-gather/reduce-scatter rewrites (transforms/fsdp.py), bucketing
(bucketing.py), and wait sorting (distributed/utils.py `sort_waits:115`).

Plans compose:
- **FSDP** (ZeRO): every weight sharded on its *largest* dim over the
  ``fsdp`` axis; params are all-gathered just-in-time per layer by the
  partitioner, grads reduce-scattered — the ZeRO-3 dataflow of the
  reference's `rematerialize_all_gather` without a bespoke pass.
- **TP** (Megatron): qkv/fc projections column-parallel, output projections
  row-parallel, so each block needs a single psum per matmul pair riding ICI.
- **DP**: the batch dim of activations shards over (dp, fsdp) jointly.
"""

from __future__ import annotations

from typing import Any, Optional

from thunder_tpu.models.gpt import GPTConfig


def _P(*parts):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*parts)


def _axis(mesh, name: str) -> Optional[str]:
    """Axis name if present in the mesh with size > 1, else None."""
    if mesh is None:
        return None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return name if sizes.get(name, 1) > 1 else None


def _div(n: int, axis_size: int) -> bool:
    return axis_size > 0 and n % axis_size == 0


def gpt_param_specs(config: GPTConfig, mesh, *, fsdp: bool = True, tp: bool = True) -> dict:
    """PartitionSpec pytree matching ``models.gpt.init_params`` structure."""
    fs = _axis(mesh, "fsdp") if fsdp else None
    tpx = _axis(mesh, "tp") if tp else None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape)) if mesh is not None else {}
    fs_n = sizes.get("fsdp", 1)
    tp_n = sizes.get("tp", 1)

    C = config

    def spec2d(rows: int, cols: int, *, col_parallel: Optional[bool]):
        """(rows, cols) weight: TP on the compute-parallel dim, FSDP on the
        other (or on rows when no TP)."""
        row_ax = col_ax = None
        if col_parallel is True and tpx and _div(rows, tp_n):
            row_ax = tpx
        elif col_parallel is False and tpx and _div(cols, tp_n):
            col_ax = tpx
        if fs:
            if row_ax is None and _div(rows, fs_n):
                row_ax = (row_ax, fs) if row_ax else fs
            elif col_ax is None and _div(cols, fs_n):
                col_ax = fs
        return _P(row_ax, col_ax)

    def norm_spec(p: dict) -> dict:
        return {k: _P(None) for k in p}

    def block_spec(blk: dict) -> dict:
        s: dict[str, Any] = {
            "norm_1": norm_spec(blk["norm_1"]),
            "attn": {},
            "mlp": {},
        }
        if "norm_2" in blk:
            s["norm_2"] = norm_spec(blk["norm_2"])
        a = blk["attn"]
        s["attn"]["qkv_w"] = spec2d(C.qkv_out, C.n_embd, col_parallel=True)
        s["attn"]["proj_w"] = spec2d(C.n_embd, C.n_head * C.head_size, col_parallel=False)
        if "qkv_b" in a:
            s["attn"]["qkv_b"] = _P(tpx if tpx and _div(C.qkv_out, tp_n) else None)
        if "proj_b" in a:
            s["attn"]["proj_b"] = _P(None)
        mlp = blk["mlp"]
        hidden = C.mlp_hidden
        if "fc_1_w" in mlp:
            s["mlp"]["fc_1_w"] = spec2d(hidden, C.n_embd, col_parallel=True)
            s["mlp"]["fc_2_w"] = spec2d(hidden, C.n_embd, col_parallel=True)
            s["mlp"]["proj_w"] = spec2d(C.n_embd, hidden, col_parallel=False)
        if "fc_w" in mlp:
            s["mlp"]["fc_w"] = spec2d(hidden, C.n_embd, col_parallel=True)
            s["mlp"]["proj_w"] = spec2d(C.n_embd, hidden, col_parallel=False)
        for b_name in ("fc_1_b", "fc_2_b", "fc_b"):
            if b_name in mlp:
                s["mlp"][b_name] = _P(tpx if tpx and _div(hidden, tp_n) else None)
        if "proj_b" in mlp:
            s["mlp"]["proj_b"] = _P(None)
        return s

    # Embedding / head: vocab-parallel over tp, fsdp on the other dim.
    return {
        "wte": spec2d(C.padded_vocab_size, C.n_embd, col_parallel=True),
        "blocks": [block_spec(b) for b in _blocks_template(config)],
        "ln_f": {"weight": _P(None), **({"bias": _P(None)} if C.norm_class == "LayerNorm" else {})},
        "lm_head_w": spec2d(C.padded_vocab_size, C.n_embd, col_parallel=True),
    }


def _blocks_template(config: GPTConfig) -> list[dict]:
    """Structure-only template of one block's param dict (no arrays)."""
    blk: dict[str, Any] = {
        "norm_1": {"weight": 0, **({"bias": 0} if config.norm_class == "LayerNorm" else {})},
        "attn": {"qkv_w": 0, "proj_w": 0, **({"qkv_b": 0, "proj_b": 0} if config.bias else {})},
        "mlp": {},
    }
    if not config.shared_attention_norm:
        blk["norm_2"] = dict(blk["norm_1"])
    if config.mlp_class == "LLaMAMLP":
        blk["mlp"] = {"fc_1_w": 0, "fc_2_w": 0, "proj_w": 0}
        if config.bias:
            blk["mlp"].update({"fc_1_b": 0, "fc_2_b": 0, "proj_b": 0})
    else:
        blk["mlp"] = {"fc_w": 0, "proj_w": 0}
        if config.bias:
            blk["mlp"].update({"fc_b": 0, "proj_b": 0})
    return [blk for _ in range(config.n_layer)]


def data_spec(mesh):
    """Batch sharding for (B, T) token tensors: batch over (dp, fsdp)."""
    batch_axes = tuple(a for a in ("dp", "fsdp") if _axis(mesh, a))
    seq_ax = _axis(mesh, "sp")
    return _P(batch_axes if batch_axes else None, seq_ax)


def named_shardings(mesh, specs):
    from jax.sharding import NamedSharding
    from thunder_tpu.core.pytree import tree_map

    return tree_map(lambda s: NamedSharding(mesh, s), specs,
                    is_leaf=lambda x: type(x).__name__ == "PartitionSpec")


def shard_pytree(tree, mesh, specs):
    """device_put a pytree onto the mesh per its spec pytree."""
    import jax
    from thunder_tpu.core.pytree import tree_flatten, tree_unflatten

    flat, spec_struct = tree_flatten(tree)
    flat_specs, _ = tree_flatten(specs, is_leaf=lambda x: type(x).__name__ == "PartitionSpec")
    from jax.sharding import NamedSharding

    out = [jax.device_put(x, NamedSharding(mesh, s)) for x, s in zip(flat, flat_specs)]
    return tree_unflatten(spec_struct, out)


def gather_pytree(tree):
    """Every (possibly sharded) jax leaf gathered to a host numpy array —
    the mesh-independent intermediate of a reshard. Multi-process arrays go
    through ``process_allgather`` (distributed/checkpoint.gather_full)."""
    from thunder_tpu.distributed.checkpoint import gather_full

    return gather_full(tree)


def reshard_pytree(tree, mesh, specs):
    """Re-lay-out a pytree onto a (possibly different-shape) mesh per
    ``specs``: gather to host, then :func:`shard_pytree` onto the target.

    This is the small-state elastic-resume path (``resilience/elastic.py``)
    — values are bit-identical after the round trip (only the layout
    changes); at checkpoint scale the Orbax restore
    (``distributed/checkpoint.load(mesh=..., specs=...)``) reads only the
    byte ranges each surviving device needs instead of materializing full
    arrays."""
    return shard_pytree(gather_pytree(tree), mesh, specs)
