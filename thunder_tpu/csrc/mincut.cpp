// Dinic max-flow / min-cut for the rematerialization pass.
//
// Reference parity: thunder/core/rematerialization.py:245 uses igraph's
// C max-flow for the save-vs-recompute cut between forward and backward
// traces; this is the equivalent native component, built in-repo (C++,
// ~150 LoC) instead of an external library dependency.
//
// C ABI:
//   tt_mincut(n, m, edges_u, edges_v, caps, s, t, side_out) -> maxflow
//     n nodes, m directed edges (u->v with capacity caps[i], int64;
//     capacity INT64_MAX/4 treated as infinite). After the run,
//     side_out[i] = 1 if node i is reachable from s in the residual
//     graph (source side of the min cut), else 0.
//
// Build: g++ -O2 -shared -fPIC mincut.cpp -o libttmincut.so

#include <cstdint>
#include <cstring>
#include <queue>
#include <vector>

namespace {

struct Edge {
  int to;
  int64_t cap;
  int rev;  // index of reverse edge in graph[to]
};

struct Dinic {
  std::vector<std::vector<Edge>> g;
  std::vector<int> level, iter;

  explicit Dinic(int n) : g(n), level(n), iter(n) {}

  void add_edge(int u, int v, int64_t cap) {
    g[u].push_back({v, cap, static_cast<int>(g[v].size())});
    g[v].push_back({u, 0, static_cast<int>(g[u].size()) - 1});
  }

  bool bfs(int s, int t) {
    std::fill(level.begin(), level.end(), -1);
    std::queue<int> q;
    level[s] = 0;
    q.push(s);
    while (!q.empty()) {
      int u = q.front();
      q.pop();
      for (const Edge& e : g[u]) {
        if (e.cap > 0 && level[e.to] < 0) {
          level[e.to] = level[u] + 1;
          q.push(e.to);
        }
      }
    }
    return level[t] >= 0;
  }

  int64_t dfs(int u, int t, int64_t f) {
    if (u == t) return f;
    for (int& i = iter[u]; i < static_cast<int>(g[u].size()); ++i) {
      Edge& e = g[u][i];
      if (e.cap > 0 && level[u] < level[e.to]) {
        int64_t d = dfs(e.to, t, f < e.cap ? f : e.cap);
        if (d > 0) {
          e.cap -= d;
          g[e.to][e.rev].cap += d;
          return d;
        }
      }
    }
    return 0;
  }

  int64_t max_flow(int s, int t) {
    int64_t flow = 0;
    const int64_t INF = INT64_MAX / 2;
    while (bfs(s, t)) {
      std::fill(iter.begin(), iter.end(), 0);
      int64_t f;
      while ((f = dfs(s, t, INF)) > 0) flow += f;
    }
    return flow;
  }

  void source_side(int s, uint8_t* side) {
    std::queue<int> q;
    q.push(s);
    side[s] = 1;
    while (!q.empty()) {
      int u = q.front();
      q.pop();
      for (const Edge& e : g[u]) {
        if (e.cap > 0 && !side[e.to]) {
          side[e.to] = 1;
          q.push(e.to);
        }
      }
    }
  }
};

}  // namespace

extern "C" int64_t tt_mincut(int32_t n, int32_t m, const int32_t* edges_u,
                             const int32_t* edges_v, const int64_t* caps,
                             int32_t s, int32_t t, uint8_t* side_out) {
  Dinic d(n);
  for (int i = 0; i < m; ++i) d.add_edge(edges_u[i], edges_v[i], caps[i]);
  int64_t flow = d.max_flow(s, t);
  std::memset(side_out, 0, n);
  d.source_side(s, side_out);
  return flow;
}
