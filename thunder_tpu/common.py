"""CompileData / CompileStats / CacheEntry.

Reference parity: thunder/common.py (`CompileData:138`, `CompileStats:54`,
`CacheEntry` in thunder/__init__.py:281) and thunder/core/options.py
(CACHE_OPTIONS, SHARP_EDGES_OPTIONS).
"""

from __future__ import annotations

import contextlib
import contextvars
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence


class CACHE_OPTIONS(enum.Enum):
    NO_CACHING = enum.auto()
    CONSTANT_VALUES = enum.auto()
    SAME_INPUT = enum.auto()
    SYMBOLIC_VALUES = enum.auto()  # reserved, as in the reference


_string_to_cache_option = {
    "no caching": CACHE_OPTIONS.NO_CACHING,
    "constant values": CACHE_OPTIONS.CONSTANT_VALUES,
    "same input": CACHE_OPTIONS.SAME_INPUT,
    "symbolic values": CACHE_OPTIONS.SYMBOLIC_VALUES,
}


def resolve_cache_option(x: Any) -> CACHE_OPTIONS:
    if isinstance(x, CACHE_OPTIONS):
        return x
    if isinstance(x, str):
        opt = _string_to_cache_option.get(x.lower())
        if opt is not None:
            return opt
    raise ValueError(f"Unknown cache option {x!r}")


class SHARP_EDGES_OPTIONS(enum.Enum):
    ALLOW = enum.auto()
    WARN = enum.auto()
    ERROR = enum.auto()


_string_to_sharp_edges = {
    "allow": SHARP_EDGES_OPTIONS.ALLOW,
    "warn": SHARP_EDGES_OPTIONS.WARN,
    "error": SHARP_EDGES_OPTIONS.ERROR,
}


def resolve_sharp_edges_option(x: Any) -> SHARP_EDGES_OPTIONS:
    if isinstance(x, SHARP_EDGES_OPTIONS):
        return x
    if isinstance(x, str):
        opt = _string_to_sharp_edges.get(x.lower())
        if opt is not None:
            return opt
    raise ValueError(f"Unknown sharp_edges option {x!r} (allow|warn|error)")


class ThunderSharpEdgeWarning(UserWarning):
    """A tracing-unsafe construct was observed (reference:
    thunder/core/options.py:146 + jit_ext.py `_general_jit_sharp_edge:468`)."""


class ThunderSharpEdgeError(RuntimeError):
    """sharp_edges='error': a tracing-unsafe construct was observed."""


_sharp_edges_policy = contextvars.ContextVar(
    "sharp_edges_policy", default=SHARP_EDGES_OPTIONS.ALLOW
)


_sharp_edges_suppressed = contextvars.ContextVar("sharp_edges_suppressed", default=False)


@contextlib.contextmanager
def suppress_sharp_edges():
    """Scope for framework-internal work during tracing (e.g. guarded
    concretization) whose own env/clock reads are not USER sharp edges."""
    tok = _sharp_edges_suppressed.set(True)
    try:
        yield
    finally:
        _sharp_edges_suppressed.reset(tok)


def sharp_edge(msg: str) -> None:
    """Report a tracing-unsafe construct per the active policy. ALLOW is
    silent (the reference's default); WARN emits ThunderSharpEdgeWarning;
    ERROR raises ThunderSharpEdgeError."""
    if _sharp_edges_suppressed.get():
        return
    policy = _sharp_edges_policy.get()
    # Observability tap (before the ALLOW early-return: the event log wants
    # every sharp edge, the policy only governs warn/raise behavior).
    from thunder_tpu.observability import events, metrics as obsm

    if obsm.enabled():
        obsm.SHARP_EDGES.inc()
    if events.active_log() is not None:
        events.emit_event("sharp_edge", message=msg, policy=policy.name.lower())
    if policy is SHARP_EDGES_OPTIONS.ALLOW:
        return
    full = (
        f"sharp edge: {msg}. The trace specializes on the observed value; "
        f"changes to it will NOT recompile. Pass sharp_edges='allow' to silence."
    )
    if policy is SHARP_EDGES_OPTIONS.ERROR:
        raise ThunderSharpEdgeError(full)
    import warnings

    warnings.warn(full, ThunderSharpEdgeWarning, stacklevel=3)


@contextlib.contextmanager
def sharp_edges_policy(policy: SHARP_EDGES_OPTIONS):
    tok = _sharp_edges_policy.set(policy)
    try:
        yield
    finally:
        _sharp_edges_policy.reset(tok)


@dataclass
class CompileData:
    """Options resolved at jit() time (reference: thunder/common.py:138)."""

    fn: Callable
    executors_list: tuple = ()
    cache_option: CACHE_OPTIONS = CACHE_OPTIONS.CONSTANT_VALUES
    sharp_edges: SHARP_EDGES_OPTIONS = SHARP_EDGES_OPTIONS.ALLOW
    disable_jit_staging: bool = False
    is_module: bool = False
    compile_options: dict = field(default_factory=dict)
    # Distributed state (set by thunder_tpu.parallel transforms)
    use_ddp: bool = False
    use_fsdp: bool = False
    process_group: Any = None
    _used_options: dict = field(default_factory=dict)

    def get_compile_option(self, name: str, doc: str) -> Any:
        self._used_options[name] = doc
        return self.compile_options.get(name)

    def last_compile_options(self) -> dict:
        return dict(self._used_options)


class EntryStats:
    """Per-cache-entry counters (ISSUE 2: cache observability)."""

    __slots__ = ("hits", "fast_hits", "prologue_runs", "guard_fails", "trace_s",
                 "first_run_s", "degradation_level", "phases",
                 "predicted_peak_bytes")

    def __init__(self):
        self.hits = 0  # times this entry served a call
        self.fast_hits = 0  # ... of which via the O(1) key fast path
        self.prologue_runs = 0  # times this entry's prologue executed
        self.guard_fails = 0  # prologue/value-guard rejections during probes
        self.trace_s = 0.0  # host tracing+transform time building this entry
        self.first_run_s = 0.0  # first execution (includes the XLA compile)
        # De-opt ladder position this entry compiled at (resilience/deopt.py):
        # 0 normal, 1 no fusion/donation, 2 + aggressive remat, 3 + exact
        # shapes. Surfaced per entry by thunder_tpu.cache_info.
        self.degradation_level = 0
        # Compile-phase spans (seconds) of this entry's build: trace /
        # transforms / claim / static_analysis / staging / xla_compile, plus
        # the persistent XLA cache verdict ("persistent_cache": "hit"|"miss")
        # when jax's cache resolved the first run. Mirrors the compile_phase
        # events.
        self.phases: dict = {}
        # Static liveness planner's predicted per-device peak HBM for this
        # entry (analysis/liveness.py; None when planning failed or was
        # skipped) — what the de-opt ladder consults to jump levels.
        self.predicted_peak_bytes = None

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


@dataclass
class CacheEntry:
    """One compiled specialization (reference: thunder/__init__.py:281)."""

    prologue_fn: Callable
    computation_fn: Callable
    epilogue_fn: Optional[Callable]
    backward_fn: Optional[Callable]
    prologue_traces: list
    computation_traces: list
    backward_traces: list
    return_none_instead_of_grads: bool = False
    torch_facing: bool = False
    needs_rng: bool = False
    # Guards over input-derived scalar values that the trace specialized on
    # (core/concrete.py): all must re-evaluate equal for a cache hit.
    value_guards: tuple = ()
    # Symbolic-values caching (core/bucketing.SymbolicSpec) — None for exact
    # entries. When set, dispatch pads marked dims to the bucket ceiling,
    # appends true-extent scalars for masked reductions, and crops outputs.
    sym_spec: Any = None
    # Shape-class record for automatic symbolic-dim detection: the flatten
    # treedef and per-leaf metadata of the inputs this entry was built from.
    treedef: Any = None
    leaf_meta: tuple = ()
    # Post-step isfinite guard policy (jit(on_nan=...)): None disables the
    # check; "rerun-instrumented" re-runs via claimed_extrace — the claimed
    # (pre-instrumentation, pre-del) execution trace — under a NaN watcher
    # to attribute the producing op (resilience/deopt.py).
    on_nan: Any = None
    claimed_extrace: Any = None
    # The compile_scope id this entry was built under: the first run happens
    # after the scope exits, so the xla_compile phase event needs the id
    # carried explicitly to correlate with the build's compile_phase events.
    compile_id: Any = None
    # Lazily-resolved "L<idx>.<sym>" labels of the execution trace's
    # collective dispatch sites (None = not yet computed, () = none): what
    # the collective watchdog names in a CollectiveTimeoutError and the
    # gate deciding whether a dispatch is guarded at all (api._run_entry).
    collective_lines: Any = None
    # Static planner artifacts (ISSUE 10; api._compile_entry_impl's
    # static_analysis phase): the schedule certificate the watchdog's
    # timeout diagnosis consumes, and the last call's true bucket extents
    # (set per dispatch) so the de-opt ladder can price the L3 exact-shape
    # level for the failing call.
    schedule_certificate: Any = None
    last_true_extents: Any = None
    stats: EntryStats = field(default_factory=EntryStats)


class CompileStats:
    """Timers, caches, trace history (reference: thunder/common.py:54)."""

    def __init__(self):
        self.cache_entries: list[CacheEntry] = []
        self.cache_hits: int = 0
        self.cache_misses: int = 0
        self.calls: int = 0
        self.last_traces: list = []
        self.last_prologue_traces: list = []
        self.last_backward_traces: list = []
        # O(1) dispatch fast path: (treedef, leaf metadata) -> CacheEntry,
        # learned on the first slow (prologue-scanning) hit for a key. Bounded;
        # cleared wholesale on overflow (keys regenerate on the next slow hit).
        self.fast_cache: dict = {}
        self.fast_hits: int = 0
        self.slow_hits: int = 0
        self.prologue_runs: int = 0
        # Compile-side counters/accumulators (ISSUE 2: cache observability).
        self.compile_count: int = 0
        self.trace_seconds: float = 0.0
        self.first_run_seconds: float = 0.0
        self.cache_lookup_ns: int = 0
        # nanosecond timers
        self.last_trace_host_start: int = 0
        self.last_trace_host_stop: int = 0
        self.last_trace_cache_start: int = 0
        self.last_trace_cache_stop: int = 0
        self.last_trace_tracing_start: int = 0
        self.last_trace_tracing_stop: int = 0
        self.last_trace_host_execution_start: int = 0
        self.last_trace_host_execution_stop: int = 0

    @property
    def last_compile_time_ms(self) -> float:
        return (self.last_trace_tracing_stop - self.last_trace_tracing_start) / 1e6

    @property
    def recompile_count(self) -> int:
        """Compiles beyond the first — the recompile-storm signal."""
        return max(0, self.compile_count - 1)

    @property
    def last_cache_lookup_us(self) -> float:
        return (self.last_trace_cache_stop - self.last_trace_cache_start) / 1e3


def timer_ns() -> int:
    return time.perf_counter_ns()
