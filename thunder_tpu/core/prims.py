"""The primitive operation set: the trace IR's reduced instruction set.

Reference parity: thunder/core/prims.py (`PrimIDs:94-249`, `OpTags:252`,
`make_prim:267`) — ~125 primitives spanning unpack/check guards, utility ops,
data movement, tensor creation, shape ops, elementwise unary/binary/ternary,
reductions, scatter/gather, and linear algebra. Each prim has a *meta*
function performing shape/dtype inference over proxies; concrete semantics
live in executors (thunder_tpu/executors/jaxex.py maps every prim to
jax.numpy/lax, which XLA fuses and tiles onto the TPU MXU/VPU).

Prims are deliberately strict: elementwise prims require same-shape,
same-dtype inputs. Broadcasting and type promotion happen one level up, in
the clang layer — keeping prims trivially lowerable to `lax` ops with no
hidden semantics.

RNG prims are functional: a trace containing them is given an explicit
``rng_key`` input by the RNG transform (TPU-first: threefry keys, not a
stateful Philox offset as in the reference's `uniform_philox`).
"""

from __future__ import annotations

import enum
from numbers import Number
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core import codeutils, dtypes, devices, utils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.langctxs import LanguageContext, Languages, register_langctx
from thunder_tpu.core.proxies import (
    AnyProxy,
    CollectionProxy,
    FutureTensorProxy,
    NumberProxy,
    Proxy,
    StringProxy,
    TensorProxy,
    proxy,
    pyval,
)
from thunder_tpu.core.symbol import Symbol
from thunder_tpu.core.utils import (
    ELEMENTWISE_TYPE_PROMOTION_KIND,
    canonicalize_dim,
    canonicalize_dims,
    compute_broadcast_shape,
)


class OpTags(enum.Enum):
    """Reference parity: thunder/core/prims.py `OpTags:252`."""

    REDUCTION_OP = enum.auto()
    SHAPE_OP = enum.auto()
    ELEMENTWISE_UNARY_OP = enum.auto()
    ELEMENTWISE_BINARY_OP = enum.auto()
    MATMUL_OP = enum.auto()
    RANDOM_OP = enum.auto()
    DEVICE_SYNC_OP = enum.auto()
    DONT_DCE = enum.auto()
    UNPACK_OP = enum.auto()
    GUARD_OP = enum.auto()
    COMM_OP = enum.auto()
    # Observable-effect tags: the single source of truth shared by DCE
    # (transforms/common.py), del_last_used, and the analysis/ verifier's
    # dce.dead-symbol and alias.inplace-hazard rules.
    SIDE_EFFECT = enum.auto()  # op does I/O or otherwise acts beyond its outputs
    IN_PLACE = enum.auto()  # op mutates an operand (see analysis.rules.INPLACE_MUTATED_ARG)


class PrimIDs(enum.Enum):
    # Unpacking and checking (prologue guards)
    UNPACK_TRIVIAL = enum.auto()
    TENSOR_CONSTANT = enum.auto()
    UNPACK_SEQUENCE = enum.auto()
    UNPACK_KEY = enum.auto()
    UNPACK_ATTR = enum.auto()
    CHECK_TENSOR_SHAPE_AND_METADATA = enum.auto()
    CHECK_NUMBER_TYPE_AND_VALUE = enum.auto()
    CHECK_STRING_VALUE = enum.auto()
    CHECK_LEN = enum.auto()
    CHECK_KEYS = enum.auto()
    CHECK_NONE = enum.auto()
    # Symbolic-values caching (cache="symbolic values"): a marked tensor dim
    # is lifted into a NumberProxy by UNPACK_DIM and constrained by
    # CHECK_DIM_BUCKET instead of the exact-extent metadata check.
    UNPACK_DIM = enum.auto()
    CHECK_DIM_BUCKET = enum.auto()
    # Utility
    DEL = enum.auto()
    RETURN = enum.auto()
    COMMENT = enum.auto()
    PRINT = enum.auto()
    # Data movement and host sync
    CONVERT_ELEMENT_TYPE = enum.auto()
    DEVICE_PUT = enum.auto()
    ITEM = enum.auto()
    COPY_ = enum.auto()
    SHALLOW_COPY = enum.auto()
    STOP_GRADIENT = enum.auto()
    # Tensor creation
    FULL = enum.auto()
    IOTA = enum.auto()
    UNIFORM = enum.auto()
    RANDN = enum.auto()
    UNIFORM_KEYED = enum.auto()
    RANDN_KEYED = enum.auto()
    UNIFORM_PHILOX = enum.auto()
    TENSOR_FROM_SEQUENCE = enum.auto()
    # Shape ops
    BROADCAST_IN_DIM = enum.auto()
    CAT = enum.auto()
    FLIP = enum.auto()
    PAD = enum.auto()
    RESHAPE = enum.auto()
    SLICE = enum.auto()
    SQUEEZE = enum.auto()
    TRANSPOSE = enum.auto()
    TAKE = enum.auto()
    SETITEM = enum.auto()
    TAKE_ALONG_AXIS = enum.auto()
    GATHER = enum.auto()
    SCATTER_ADD = enum.auto()
    INDEX_PUT = enum.auto()
    ARGSORT = enum.auto()
    SORT = enum.auto()
    TOPK = enum.auto()
    CUMSUM = enum.auto()
    CUMPROD = enum.auto()
    # Elementwise unary
    ABS = enum.auto()
    ACOS = enum.auto()
    ACOSH = enum.auto()
    ASIN = enum.auto()
    ASINH = enum.auto()
    ATAN = enum.auto()
    ATANH = enum.auto()
    BITWISE_NOT = enum.auto()
    CEIL = enum.auto()
    COS = enum.auto()
    COSH = enum.auto()
    DIGAMMA = enum.auto()
    ERF = enum.auto()
    ERFC = enum.auto()
    ERFINV = enum.auto()
    EXP = enum.auto()
    EXP2 = enum.auto()
    EXPM1 = enum.auto()
    FLOOR = enum.auto()
    ISFINITE = enum.auto()
    ISINF = enum.auto()
    ISNAN = enum.auto()
    LGAMMA = enum.auto()
    LOG = enum.auto()
    LOG10 = enum.auto()
    LOG1P = enum.auto()
    LOG2 = enum.auto()
    NEG = enum.auto()
    RECIPROCAL = enum.auto()
    ROUND = enum.auto()
    RSQRT = enum.auto()
    SIGN = enum.auto()
    SIGNBIT = enum.auto()
    SIN = enum.auto()
    SINH = enum.auto()
    SQRT = enum.auto()
    TAN = enum.auto()
    TANH = enum.auto()
    TRUNC = enum.auto()
    REAL = enum.auto()
    IMAG = enum.auto()
    # Elementwise binary
    ADD = enum.auto()
    ATAN2 = enum.auto()
    BITWISE_AND = enum.auto()
    BITWISE_OR = enum.auto()
    BITWISE_XOR = enum.auto()
    BITWISE_LEFT_SHIFT = enum.auto()
    BITWISE_RIGHT_SHIFT = enum.auto()
    DIV = enum.auto()
    EQ = enum.auto()
    FMOD = enum.auto()
    GE = enum.auto()
    GT = enum.auto()
    LE = enum.auto()
    LT = enum.auto()
    MAXIMUM = enum.auto()
    MINIMUM = enum.auto()
    MUL = enum.auto()
    NE = enum.auto()
    NEXTAFTER = enum.auto()
    POW = enum.auto()
    REMAINDER = enum.auto()
    SUB = enum.auto()
    COPYSIGN = enum.auto()
    ZETA = enum.auto()
    POLYGAMMA = enum.auto()
    # Conditional
    WHERE = enum.auto()
    # Reductions
    AMAX = enum.auto()
    AMIN = enum.auto()
    PROD = enum.auto()
    SUM = enum.auto()
    VAR = enum.auto()
    VAR_MEAN = enum.auto()
    ARGMAX = enum.auto()
    ARGMIN = enum.auto()
    # Linear algebra / NN
    MATMUL = enum.auto()
    LINEAR = enum.auto()
    CONVOLUTION = enum.auto()
    CONVOLUTION_BWD = enum.auto()
    EMBEDDING = enum.auto()
    EMBEDDING_BACKWARD = enum.auto()
    POOL = enum.auto()
    POOL_BWD = enum.auto()


_prims_by_id: dict[PrimIDs, Symbol] = {}


def make_prim(
    id: PrimIDs,
    name: str,
    meta: Callable,
    *,
    tags: Sequence[OpTags] = (),
    python_printer: Optional[Callable] = None,
    python_impl: Optional[Callable] = None,
) -> Symbol:
    """Reference parity: thunder/core/prims.py `make_prim:267`."""
    sym = Symbol(
        name,
        meta,
        id=id,
        is_prim=True,
        tags=tags,
        python_printer=python_printer,
        python_impl=python_impl,
        module="prims",
    )
    _prims_by_id[id] = sym
    return sym


def get_prim(id: PrimIDs) -> Symbol:
    return _prims_by_id[id]


# =============================================================================
# Unpacking and checking prims (prologue)
# =============================================================================


def _unpack_trivial_meta(x: Any, *, name: str) -> Any:
    return x


def _unpack_trivial_printer(bsym) -> str:
    out = bsym.output
    nm = out.name if isinstance(out, Proxy) else codeutils.prettyprint(out)
    return f"# {nm} bound by the signature"


unpack_trivial = make_prim(
    PrimIDs.UNPACK_TRIVIAL,
    "unpack_trivial",
    _unpack_trivial_meta,
    tags=(OpTags.UNPACK_OP, OpTags.DONT_DCE),
    python_printer=_unpack_trivial_printer,
)


class _ConstHandle:
    """Identity-hashable wrapper keeping a concrete array OFF the bound
    symbol's printable/hashable surface (CSE keys, repr) while remaining in
    its args for liveness."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __repr__(self) -> str:
        return f"<const {getattr(self.value, 'shape', ())}>"


def _tensor_constant_meta(handle: _ConstHandle):
    from thunder_tpu.core.proxies import tensorproxy_from_concrete

    return tensorproxy_from_concrete(handle.value)


def _tensor_constant_printer(bsym) -> str:
    key = next(iter(bsym._call_ctx))
    return f"{bsym.output.name} = {key}"


def _tensor_constant_bind(bsym) -> None:
    handle = bsym.args[0]
    bsym._call_ctx[f"_tconst_{id(handle)}"] = handle.value
    # Provenance comment in the generated program (the VM records where
    # every value was loaded from — interpreter.py provenance; here the
    # trace documents what was captured).
    v = handle.value
    bsym.header = (
        f"captured tensor constant: shape {tuple(getattr(v, 'shape', ()))} "
        f"dtype {getattr(v, 'dtype', '?')} (baked; not a guarded input)"
    )


tensor_constant_sym = make_prim(
    PrimIDs.TENSOR_CONSTANT,
    "tensor_constant",
    _tensor_constant_meta,
    python_printer=_tensor_constant_printer,
)
tensor_constant_sym._bind_postprocess = _tensor_constant_bind


def tensor_constant(value):
    """Lift a concrete array (numpy/torch/jax) captured from the enclosing
    Python scope into the trace as a BAKED constant.

    Reference analogue: the bytecode VM proxies tensors wherever it loads
    them (closures, globals, defaults — interpreter.py provenance records);
    the dispatch frontend lifts them at the op boundary instead. The value
    is bound into the generated program's exec namespace via the bound
    symbol's call ctx — it is part of the compiled program, NOT a guarded
    input (mutating the captured array later is invisible, exactly like a
    baked Python-number constant).

    Per-trace memo: the same captured object used by N ops bakes ONE
    constant (one device buffer, one bound symbol) — identity-hashed
    handles would otherwise defeat CSE and pin N copies."""
    from thunder_tpu.core.trace import get_tracectx
    from thunder_tpu.executors import bridge

    trc = get_tracectx()
    memo = getattr(trc, "_tconst_memo", None)
    if memo is None:
        memo = trc._tconst_memo = {}
    hit = memo.get(id(value))
    if hit is not None:
        return hit[1]
    # The reference's global-load sharp edge (jit_ext.py:468): loading a
    # tensor the prologue cannot guard is silent under "allow", loud under
    # "warn"/"error" — the baked value goes stale if the caller mutates it.
    from thunder_tpu.common import sharp_edge

    sharp_edge(
        f"captured concrete tensor (shape {tuple(getattr(value, 'shape', ()))}) "
        "baked into the trace as a constant — it is not a guarded input; "
        "later mutation of the captured array will NOT be seen. Pass it as "
        "an argument to make it an input"
    )
    proxy = tensor_constant_sym(_ConstHandle(bridge.to_jax(value)))
    # Keep the source object alive for the trace's lifetime so its id can't
    # be reused by a different array.
    memo[id(value)] = (value, proxy)
    return proxy


def _unpack_sequence_meta(seq: Any, length: int) -> list:
    coll = seq.coll if isinstance(seq, CollectionProxy) else seq
    check(len(coll) == length, lambda: f"Expected sequence of length {length}")

    def elem_proxy(x):
        if isinstance(x, Proxy):
            return x
        if isinstance(x, (tuple, list, dict)):
            return CollectionProxy(x)
        return proxy(x)

    return [elem_proxy(x) for x in coll]


def _unpack_sequence_printer(bsym) -> str:
    src = bsym.args[0]
    src_s = src.name if isinstance(src, Proxy) else codeutils.prettyprint(src)
    if not bsym.output:  # empty sequence: nothing to bind (check_len guards it)
        return f"_ = {src_s}"
    outs = ", ".join(
        o.name if isinstance(o, Proxy) else codeutils.prettyprint(o) for o in bsym.output
    )
    return f"{outs}, = {src_s}" if len(bsym.output) == 1 else f"{outs} = {src_s}"


unpack_sequence = make_prim(
    PrimIDs.UNPACK_SEQUENCE,
    "unpack_sequence",
    _unpack_sequence_meta,
    tags=(OpTags.UNPACK_OP, OpTags.DONT_DCE),
    python_printer=_unpack_sequence_printer,
)


def _unpack_key_meta(d: Any, key: str) -> Any:
    coll = d.coll if isinstance(d, CollectionProxy) else d
    v = coll[key]
    return proxy(v) if not isinstance(v, Proxy) else v


def _unpack_key_printer(bsym) -> str:
    out = bsym.output
    d, key = bsym.args
    d_s = d.name if isinstance(d, Proxy) else codeutils.prettyprint(d)
    return f"{out.name} = {d_s}[{key!r}]"


unpack_key = make_prim(
    PrimIDs.UNPACK_KEY,
    "unpack_key",
    _unpack_key_meta,
    tags=(OpTags.UNPACK_OP, OpTags.DONT_DCE),
    python_printer=_unpack_key_printer,
)


def _unpack_attr_meta(obj: Any, name: str) -> Any:
    v = getattr(obj, name)
    return proxy(v) if not isinstance(v, Proxy) else v


def _unpack_attr_printer(bsym) -> str:
    obj, name = bsym.args
    obj_s = obj.name if isinstance(obj, Proxy) else codeutils.prettyprint(obj)
    return f"{bsym.output.name} = getattr({obj_s}, {name!r})"


unpack_attr = make_prim(
    PrimIDs.UNPACK_ATTR,
    "unpack_attr",
    _unpack_attr_meta,
    tags=(OpTags.UNPACK_OP, OpTags.DONT_DCE),
    python_printer=_unpack_attr_printer,
)


def _check_tensor_metadata_meta(
    t: TensorProxy, shape: tuple, device: str, dtype: dtypes.dtype, requires_grad: bool, framework: str = "any"
) -> None:
    return None


def _check_tensor_metadata_impl(t, shape, device, dtype, requires_grad, framework="any") -> None:
    from thunder_tpu.core.baseutils import GuardFailure
    from thunder_tpu.executors.bridge import framework_of, is_concrete_tensor, tensor_metadata

    if not is_concrete_tensor(t):
        raise GuardFailure(f"Expected a tensor, got {type(t).__name__}")
    actual_shape, actual_device, actual_dtype, actual_rg = tensor_metadata(t)
    # A None extent is a symbolic (wildcard) dim: only the rank is enforced
    # here — the dim's value is unpacked by unpack_dim and constrained by
    # check_dim_bucket (cache="symbolic values").
    if (
        len(actual_shape) != len(shape)
        or any(s is not None and int(a) != int(s) for a, s in zip(actual_shape, shape))
        or actual_dtype != dtype
        or actual_rg != requires_grad
        or actual_device.split(":")[0] != str(device).split(":")[0]
        or (framework != "any" and framework_of(t) != framework)
    ):
        raise GuardFailure(
            f"Tensor metadata changed: expected {tuple(shape)}/{dtype}/{device}/rg={requires_grad}/{framework}, "
            f"got {tuple(actual_shape)}/{actual_dtype}/{actual_device}/rg={actual_rg}/{framework_of(t)}"
        )


check_tensor_shape_and_metadata = make_prim(
    PrimIDs.CHECK_TENSOR_SHAPE_AND_METADATA,
    "check_tensor_shape_and_metadata",
    _check_tensor_metadata_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_tensor_metadata_impl,
)


def _check_number_meta(n: Any, value: Number) -> None:
    return None


def _check_number_impl(n, value) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    if isinstance(n, NumberProxy):
        n = n.value
    if type(n) is not type(value):
        raise GuardFailure(f"Number type changed: expected {type(value).__name__}, got {type(n).__name__}")
    if not (n == value or (n != n and value != value)):
        raise GuardFailure(f"Number value changed: expected {value}, got {n}")


check_number_type_and_value = make_prim(
    PrimIDs.CHECK_NUMBER_TYPE_AND_VALUE,
    "check_number_type_and_value",
    _check_number_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_number_impl,
)


def _check_string_meta(s: Any, value: str) -> None:
    return None


def _check_string_impl(s, value) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    if s != value:
        raise GuardFailure(f"String value changed: expected {value!r}, got {s!r}")


check_string_value = make_prim(
    PrimIDs.CHECK_STRING_VALUE,
    "check_string_value",
    _check_string_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_string_impl,
)


def _check_len_meta(seq: Any, length: int) -> None:
    return None


def _check_len_impl(seq, length) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    try:
        n = len(seq)
    except TypeError:
        raise GuardFailure(f"Expected a sized collection, got {type(seq).__name__}")
    if n != length:
        raise GuardFailure(f"Length changed: expected {length}, got {n}")


def _check_keys_meta(d: Any, keys: tuple) -> None:
    return None


def _check_keys_impl(d, keys) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    try:
        actual = tuple(d.keys())
    except AttributeError:
        raise GuardFailure(f"Expected a mapping, got {type(d).__name__}")
    # Order-insensitive: unpacking is key-based and leaf order sorts keys,
    # so {'a':..,'b':..} and {'b':..,'a':..} share a cache entry.
    if len(actual) != len(keys) or set(actual) != set(keys):
        raise GuardFailure(f"Dict keys changed: expected {tuple(keys)}, got {actual}")


check_keys = make_prim(
    PrimIDs.CHECK_KEYS,
    "check_keys",
    _check_keys_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_keys_impl,
)


check_len = make_prim(
    PrimIDs.CHECK_LEN,
    "check_len",
    _check_len_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_len_impl,
)


def _check_none_meta(x: Any) -> None:
    return None


def _check_none_impl(x) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    if x is not None:
        raise GuardFailure(f"Expected None, got {type(x)}")


check_none = make_prim(
    PrimIDs.CHECK_NONE,
    "check_none",
    _check_none_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_none_impl,
)


def _unpack_dim_meta(t: TensorProxy, dim: int) -> NumberProxy:
    # The observed (bucket-padded) extent is the known value; the proxy IS
    # the symbolic dim — the "lifted NumberProxy" of symbolic-values caching.
    from thunder_tpu.core.proxies import IntegerProxy

    return IntegerProxy(int(t.shape[dim]))


def _unpack_dim_impl(t, dim: int) -> int:
    return int(t.shape[dim])


def _unpack_dim_printer(bsym) -> str:
    t, dim = bsym.args
    t_s = t.name if isinstance(t, Proxy) else codeutils.prettyprint(t)
    return f"{bsym.output.name} = {t_s}.shape[{dim}]"


unpack_dim = make_prim(
    PrimIDs.UNPACK_DIM,
    "unpack_dim",
    _unpack_dim_meta,
    tags=(OpTags.UNPACK_OP, OpTags.DONT_DCE),
    python_impl=_unpack_dim_impl,
    python_printer=_unpack_dim_printer,
)


def _check_dim_bucket_meta(d: Any, lo: int, hi: int) -> None:
    return None


def _check_dim_bucket_impl(d, lo: int, hi: int) -> None:
    from thunder_tpu.core.baseutils import GuardFailure

    if isinstance(d, NumberProxy):
        d = d.value
    if not (lo < d <= hi):
        raise GuardFailure(f"Dim bucket changed: expected extent in ({lo}, {hi}], got {d}")


check_dim_bucket = make_prim(
    PrimIDs.CHECK_DIM_BUCKET,
    "check_dim_bucket",
    _check_dim_bucket_meta,
    tags=(OpTags.GUARD_OP, OpTags.DONT_DCE),
    python_impl=_check_dim_bucket_impl,
)


# =============================================================================
# Utility prims
# =============================================================================


def _del_meta(*args) -> None:
    return None


def _del_printer(bsym) -> str:
    names = ", ".join(a.name for a in bsym.args)
    return f"del {names}"


python_del = make_prim(
    PrimIDs.DEL,
    "python_del",
    _del_meta,
    tags=(OpTags.DONT_DCE,),
    python_printer=_del_printer,
)


def _return_meta(*args) -> None:
    return None


def _return_printer(bsym) -> str:
    if len(bsym.args) == 1:
        return f"return {codeutils.prettyprint(bsym.args[0])}"
    return f"return {codeutils.prettyprint(tuple(bsym.args))}"


python_return = make_prim(
    PrimIDs.RETURN,
    "python_return",
    _return_meta,
    tags=(OpTags.DONT_DCE,),
    python_printer=_return_printer,
)


def _comment_meta(s: str) -> None:
    return None


def _comment_printer(bsym) -> str:
    return f"# {bsym.args[0]}"


comment = make_prim(
    PrimIDs.COMMENT,
    "comment",
    _comment_meta,
    tags=(OpTags.DONT_DCE,),
    python_printer=_comment_printer,
)


def _print_meta(s: Any) -> None:
    return None


python_print = make_prim(
    PrimIDs.PRINT,
    "python_print",
    _print_meta,
    tags=(OpTags.DONT_DCE, OpTags.SIDE_EFFECT),
    python_impl=print,
)


# =============================================================================
# Data movement
# =============================================================================


def _convert_element_type_meta(a: TensorProxy | Number, dtype: dtypes.dtype) -> TensorProxy | Number:
    if isinstance(a, TensorProxy):
        return TensorProxy(like=a, dtype=dtype)
    # number conversion
    typ = dtypes.dtype_to_numbertype(dtype)
    v = pyval(a)
    return proxy(typ(v)) if v is not None else NumberProxy(None, python_type=typ)


convert_element_type = make_prim(
    PrimIDs.CONVERT_ELEMENT_TYPE,
    "convert_element_type",
    _convert_element_type_meta,
)


def _device_put_meta(a: TensorProxy, device: devices.Device) -> TensorProxy:
    return TensorProxy(like=a, device=devices.to_device(device))


device_put = make_prim(PrimIDs.DEVICE_PUT, "device_put", _device_put_meta)


def _item_meta(a: TensorProxy) -> NumberProxy:
    check(a.numel == 1, lambda: f"item() requires a single-element tensor, got shape {a.shape}")
    typ = dtypes.dtype_to_numbertype(a.dtype)
    return NumberProxy(None, python_type=typ)


item = make_prim(PrimIDs.ITEM, "item", _item_meta, tags=(OpTags.DEVICE_SYNC_OP,))


def _shallow_copy_meta(a: TensorProxy) -> TensorProxy:
    return TensorProxy(like=a)


shallow_copy = make_prim(PrimIDs.SHALLOW_COPY, "shallow_copy", _shallow_copy_meta)


def _stop_gradient_meta(a: TensorProxy) -> TensorProxy:
    return TensorProxy(like=a, requires_grad=False)


stop_gradient = make_prim(PrimIDs.STOP_GRADIENT, "stop_gradient", _stop_gradient_meta)


def _copy__meta(src: TensorProxy, dst: TensorProxy) -> TensorProxy:
    utils.check_same_device(src, dst, op="copy_")
    return TensorProxy(like=dst)


# IN_PLACE: writes into ``dst`` — the verifier flags any later consumer of the
# pre-mutation value; SIDE_EFFECT: the write is observable beyond the output,
# so DCE must keep it even when the returned proxy goes unused.
copy_ = make_prim(
    PrimIDs.COPY_, "copy_", _copy__meta, tags=(OpTags.IN_PLACE, OpTags.SIDE_EFFECT)
)


# =============================================================================
# Tensor creation
# =============================================================================


def _full_meta(shape: Sequence[int], fill_value: Number, *, device: devices.Device, dtype: dtypes.dtype) -> TensorProxy:
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


full = make_prim(PrimIDs.FULL, "full", _full_meta)


def _iota_meta(length: Number, *, start: Number, step: Number, device: devices.Device, dtype: dtypes.dtype) -> TensorProxy:
    check(dtypes.is_exact_dtype(dtype) or dtypes.is_float_dtype(dtype), "iota requires a numeric dtype")
    return TensorProxy(shape=(int(pyval(length)),), device=devices.to_device(device), dtype=dtype)


iota = make_prim(PrimIDs.IOTA, "iota", _iota_meta)


def _uniform_meta(shape: Sequence[int], minval: Number, maxval: Number, *, device: devices.Device, dtype: dtypes.dtype) -> TensorProxy:
    check(dtypes.is_float_dtype(dtype), "uniform requires a float dtype")
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


uniform = make_prim(PrimIDs.UNIFORM, "uniform", _uniform_meta, tags=(OpTags.RANDOM_OP,))


def _randn_meta(shape: Sequence[int], *, device: devices.Device, dtype: dtypes.dtype) -> TensorProxy:
    check(dtypes.is_float_dtype(dtype), "randn requires a float dtype")
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


randn = make_prim(PrimIDs.RANDN, "randn", _randn_meta, tags=(OpTags.RANDOM_OP,))


# Keyed (functional) RNG prims: the rng functionalization pass rewrites
# UNIFORM/RANDN into these, threading an explicit threefry key input through
# the trace. TPU-first replacement for the reference's stateful
# `uniform_philox` (thunder/core/prims.py:142): the key is a real trace input
# so the whole program stays a pure function XLA can cache and replay.


def _uniform_keyed_meta(shape, minval, maxval, key: TensorProxy, salt: int, *, device, dtype) -> TensorProxy:
    check(dtypes.is_float_dtype(dtype), "uniform requires a float dtype")
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


uniform_keyed = make_prim(PrimIDs.UNIFORM_KEYED, "uniform_keyed", _uniform_keyed_meta)


def _randn_keyed_meta(shape, key: TensorProxy, salt: int, *, device, dtype) -> TensorProxy:
    check(dtypes.is_float_dtype(dtype), "randn requires a float dtype")
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


randn_keyed = make_prim(PrimIDs.RANDN_KEYED, "randn_keyed", _randn_keyed_meta)


def _tensor_from_sequence_meta(seq: Any, *, device: devices.Device, dtype: Optional[dtypes.dtype]) -> TensorProxy:
    # Infer shape/dtype from the (nested) sequence of numbers.
    def shape_of(s):
        if isinstance(s, (list, tuple)):
            if len(s) == 0:
                return (0,)
            inner = shape_of(s[0])
            return (len(s),) + inner
        return ()

    def leaf(s):
        while isinstance(s, (list, tuple)):
            s = s[0]
        return s

    shape = shape_of(seq)
    if dtype is None:
        lv = leaf(seq)
        if isinstance(lv, (list, tuple)):  # fully empty sequence
            dtype = dtypes.float32
        else:
            dtype = dtypes.to_strong(
                dtypes.numbertype_to_dtype(type(pyval(lv)) if isinstance(lv, NumberProxy) else type(lv))
            )
        if dtype == dtypes.float64:
            dtype = dtypes.float32
    return TensorProxy(shape=shape, device=devices.to_device(device), dtype=dtype)


tensor_from_sequence = make_prim(PrimIDs.TENSOR_FROM_SEQUENCE, "tensor_from_sequence", _tensor_from_sequence_meta)


# =============================================================================
# Shape ops
# =============================================================================


def _broadcast_in_dim_meta(a: TensorProxy, shape: Sequence[int], broadcast_dimensions: Sequence[int]) -> TensorProxy:
    check(len(broadcast_dimensions) == a.ndim, "broadcast_dimensions must match input rank")
    for i, d in enumerate(broadcast_dimensions):
        check(a.shape[i] == 1 or a.shape[i] == shape[d], lambda: f"Cannot broadcast {a.shape} into {shape}")
    return TensorProxy(like=a, shape=tuple(shape))


broadcast_in_dim = make_prim(
    PrimIDs.BROADCAST_IN_DIM, "broadcast_in_dim", _broadcast_in_dim_meta, tags=(OpTags.SHAPE_OP,)
)


def _cat_meta(tensors: Sequence[TensorProxy], dim: int) -> TensorProxy:
    check(len(tensors) > 0, "cat of zero tensors")
    first = tensors[0]
    dim = canonicalize_dim(first.ndim, dim)
    total = 0
    for t in tensors:
        check(t.ndim == first.ndim, "cat rank mismatch")
        for i in range(first.ndim):
            if i != dim:
                check(t.shape[i] == first.shape[i], lambda: f"cat shape mismatch at dim {i}")
        total += t.shape[dim]
    shape = list(first.shape)
    shape[dim] = total
    return TensorProxy(like=first, shape=tuple(shape))


cat = make_prim(PrimIDs.CAT, "cat", _cat_meta, tags=(OpTags.SHAPE_OP,))


def _flip_meta(a: TensorProxy, dims: Sequence[int]) -> TensorProxy:
    canonicalize_dims(a.ndim, tuple(dims))
    return TensorProxy(like=a)


flip = make_prim(PrimIDs.FLIP, "flip", _flip_meta, tags=(OpTags.SHAPE_OP,))


def _pad_meta(a: TensorProxy, padding_value: Number, padding_config: Sequence[tuple]) -> TensorProxy:
    check(len(padding_config) == a.ndim, "padding_config must have one (lo, hi, dilation) per dim")
    shape = []
    for s, (lo, hi, dil) in zip(a.shape, padding_config):
        out = s + lo + hi + max(0, s - 1) * dil
        check(out >= 0, "negative padded dimension")
        shape.append(out)
    return TensorProxy(like=a, shape=tuple(shape))


pad = make_prim(PrimIDs.PAD, "pad", _pad_meta, tags=(OpTags.SHAPE_OP,))


def _reshape_meta(a: TensorProxy, shape: Sequence[int]) -> TensorProxy:
    numel = 1
    for s in shape:
        numel *= int(s)
    check(numel == a.numel, lambda: f"reshape {a.shape} -> {tuple(shape)} changes element count")
    return TensorProxy(like=a, shape=tuple(int(s) for s in shape))


reshape = make_prim(PrimIDs.RESHAPE, "reshape", _reshape_meta, tags=(OpTags.SHAPE_OP,))


def _slice_meta(a: TensorProxy, start_indices: Sequence[int], end_indices: Sequence[int], strides: Optional[Sequence[int]] = None) -> TensorProxy:
    strides = strides if strides is not None else [1] * a.ndim
    shape = []
    for s, lo, hi, st in zip(a.shape, start_indices, end_indices, strides):
        check(0 <= lo <= hi <= s, lambda: f"invalid slice [{lo}:{hi}] for dim of size {s}")
        check(st > 0, "slice stride must be positive")
        shape.append((hi - lo + st - 1) // st)
    return TensorProxy(like=a, shape=tuple(shape))


slice_prim = make_prim(PrimIDs.SLICE, "slice_prim", _slice_meta, tags=(OpTags.SHAPE_OP,))


def _squeeze_meta(a: TensorProxy, dims: Sequence[int]) -> TensorProxy:
    dims = canonicalize_dims(a.ndim, tuple(dims))
    for d in dims:
        check(a.shape[d] == 1, lambda: f"Cannot squeeze dim {d} of size {a.shape[d]}")
    shape = [s for i, s in enumerate(a.shape) if i not in dims]
    return TensorProxy(like=a, shape=tuple(shape))


squeeze = make_prim(PrimIDs.SQUEEZE, "squeeze", _squeeze_meta, tags=(OpTags.SHAPE_OP,))


def _transpose_meta(a: TensorProxy, permutation: Sequence[int]) -> TensorProxy:
    utils.check_valid_permutation(a.ndim, permutation)
    shape = tuple(a.shape[i] for i in permutation)
    return TensorProxy(like=a, shape=shape)


transpose = make_prim(PrimIDs.TRANSPOSE, "transpose", _transpose_meta, tags=(OpTags.SHAPE_OP,))


def _setitem_meta(a: TensorProxy, key, value) -> TensorProxy:
    """Out-of-place indexed update: a copy of ``a`` with ``a[key] = value``
    applied (numpy/jax basic+advanced indexing semantics via .at[].set)."""
    return TensorProxy(like=a)


setitem = make_prim(PrimIDs.SETITEM, "setitem", _setitem_meta)


def _take_meta(a: TensorProxy, indices: TensorProxy, dim: int) -> TensorProxy:
    dim = canonicalize_dim(a.ndim, dim)
    check(dtypes.is_nonboolean_integer_dtype(indices.dtype), "take requires integer indices")
    check(indices.ndim <= 1, "take requires a 0/1-D index tensor")
    n = indices.numel if indices.ndim == 1 else 1
    shape = list(a.shape)
    if indices.ndim == 1:
        shape[dim] = n
    else:
        del shape[dim]
    return TensorProxy(like=a, shape=tuple(shape))


take = make_prim(PrimIDs.TAKE, "take", _take_meta)


def _take_along_axis_meta(a: TensorProxy, indices: TensorProxy, dim: int) -> TensorProxy:
    dim = canonicalize_dim(a.ndim, dim)
    check(indices.ndim == a.ndim, "take_along_axis requires same-rank indices")
    return TensorProxy(like=a, shape=indices.shape)


take_along_axis = make_prim(PrimIDs.TAKE_ALONG_AXIS, "take_along_axis", _take_along_axis_meta)


def _gather_meta(a: TensorProxy, indices: TensorProxy, dim: int) -> TensorProxy:
    dim = canonicalize_dim(a.ndim, dim)
    check(indices.ndim == a.ndim, "gather requires same-rank indices")
    return TensorProxy(like=a, shape=indices.shape)


gather = make_prim(PrimIDs.GATHER, "gather", _gather_meta)


def _scatter_add_meta(a: TensorProxy, indices: TensorProxy, value: TensorProxy, dim: int) -> TensorProxy:
    canonicalize_dim(a.ndim, dim)
    return TensorProxy(like=a)


scatter_add = make_prim(PrimIDs.SCATTER_ADD, "scatter_add", _scatter_add_meta)


def _index_put_meta(a: TensorProxy, indices: Sequence[TensorProxy], values: TensorProxy, accumulate: bool) -> TensorProxy:
    return TensorProxy(like=a)


index_put = make_prim(PrimIDs.INDEX_PUT, "index_put", _index_put_meta)


def _argsort_meta(a: TensorProxy, dim: int, descending: bool) -> TensorProxy:
    canonicalize_dim(a.ndim, dim)
    return TensorProxy(like=a, dtype=dtypes.int64)


argsort = make_prim(PrimIDs.ARGSORT, "argsort", _argsort_meta)


def _sort_meta(a: TensorProxy, dim: int, descending: bool) -> tuple:
    canonicalize_dim(a.ndim, dim)
    return TensorProxy(like=a), TensorProxy(like=a, dtype=dtypes.int64)


sort = make_prim(PrimIDs.SORT, "sort", _sort_meta)


def _cumsum_meta(a: TensorProxy, dim: int) -> TensorProxy:
    canonicalize_dim(a.ndim, dim)
    out_dtype = dtypes.int64 if dtypes.is_exact_dtype(a.dtype) else a.dtype
    return TensorProxy(like=a, dtype=out_dtype)


cumsum = make_prim(PrimIDs.CUMSUM, "cumsum", _cumsum_meta)


def _cumprod_meta(a: TensorProxy, dim: int) -> TensorProxy:
    canonicalize_dim(a.ndim, dim)
    out_dtype = dtypes.int64 if dtypes.is_exact_dtype(a.dtype) else a.dtype
    return TensorProxy(like=a, dtype=out_dtype)


cumprod = make_prim(PrimIDs.CUMPROD, "cumprod", _cumprod_meta)


def _topk_meta(a: TensorProxy, k: int, dim: int, largest: bool, sorted: bool) -> tuple:
    dim = canonicalize_dim(a.ndim, dim)
    check(0 <= k <= a.shape[dim], lambda: f"topk k={k} out of range for dim of size {a.shape[dim]}")
    shape = list(a.shape)
    shape[dim] = k
    return (
        TensorProxy(like=a, shape=tuple(shape)),
        TensorProxy(like=a, shape=tuple(shape), dtype=dtypes.int64),
    )


topk = make_prim(PrimIDs.TOPK, "topk", _topk_meta)


# =============================================================================
# Elementwise prims
# =============================================================================


def _number_fold(op_name: str, *args):
    """Constant-fold a number-only prim application at trace time."""
    import math

    vals = [pyval(a) for a in args]
    if any(v is None for v in vals):
        typ = args[0].python_type if isinstance(args[0], NumberProxy) else type(vals[0])
        return NumberProxy(None, python_type=typ)
    table = {
        "abs": abs,
        "ceil": math.ceil,
        "floor": math.floor,
        "neg": lambda a: -a,
        "add": lambda a, b: a + b,
        "sub": lambda a, b: a - b,
        "mul": lambda a, b: a * b,
        "div": lambda a, b: a / b,
        "pow": lambda a, b: a**b,
        "maximum": max,
        "minimum": min,
        "eq": lambda a, b: a == b,
        "ne": lambda a, b: a != b,
        "lt": lambda a, b: a < b,
        "le": lambda a, b: a <= b,
        "gt": lambda a, b: a > b,
        "ge": lambda a, b: a >= b,
        "exp": math.exp,
        "log": math.log,
        "sqrt": math.sqrt,
        "sin": math.sin,
        "cos": math.cos,
        "tanh": math.tanh,
    }
    fn = table.get(op_name)
    if fn is None:
        return NumberProxy(None, python_type=type(vals[0]))
    return proxy(fn(*vals))


def _elementwise_unary_meta_factory(name: str, *, type_promotion_kind, supported=None):
    def meta(a):
        if isinstance(a, (Number, NumberProxy)):
            return _number_fold(name, a)
        check(isinstance(a, TensorProxy), lambda: f"{name} expects a tensor or number, got {type(a)}")
        if supported is not None:
            check(a.dtype.kind in supported, lambda: f"{name} does not support dtype {a.dtype}")
        _, result_dtype = utils.elementwise_type_promotion(a, type_promotion_kind=type_promotion_kind)
        return TensorProxy(like=a, dtype=result_dtype)

    return meta


_K = ELEMENTWISE_TYPE_PROMOTION_KIND


def _make_elementwise_unary(id: PrimIDs, name: str, *, tpk=_K.PRESERVE, supported=None) -> Symbol:
    return make_prim(
        id,
        name,
        _elementwise_unary_meta_factory(name, type_promotion_kind=tpk, supported=supported),
        tags=(OpTags.ELEMENTWISE_UNARY_OP,),
    )


_float_kinds = ("float", "complex")

abs_prim = _make_elementwise_unary(PrimIDs.ABS, "abs", tpk=_K.COMPLEX_TO_FLOAT)
acos = _make_elementwise_unary(PrimIDs.ACOS, "acos", supported=_float_kinds)
acosh = _make_elementwise_unary(PrimIDs.ACOSH, "acosh", supported=_float_kinds)
asin = _make_elementwise_unary(PrimIDs.ASIN, "asin", supported=_float_kinds)
asinh = _make_elementwise_unary(PrimIDs.ASINH, "asinh", supported=_float_kinds)
atan = _make_elementwise_unary(PrimIDs.ATAN, "atan", supported=_float_kinds)
atanh = _make_elementwise_unary(PrimIDs.ATANH, "atanh", supported=_float_kinds)
bitwise_not = _make_elementwise_unary(PrimIDs.BITWISE_NOT, "bitwise_not", supported=("bool", "int", "uint"))
ceil = _make_elementwise_unary(PrimIDs.CEIL, "ceil", supported=("float",))
cos = _make_elementwise_unary(PrimIDs.COS, "cos", supported=_float_kinds)
cosh = _make_elementwise_unary(PrimIDs.COSH, "cosh", supported=_float_kinds)
digamma = _make_elementwise_unary(PrimIDs.DIGAMMA, "digamma", supported=("float",))
erf = _make_elementwise_unary(PrimIDs.ERF, "erf", supported=("float",))
erfc = _make_elementwise_unary(PrimIDs.ERFC, "erfc", supported=("float",))
erfinv = _make_elementwise_unary(PrimIDs.ERFINV, "erfinv", supported=("float",))
exp = _make_elementwise_unary(PrimIDs.EXP, "exp", supported=_float_kinds)
exp2 = _make_elementwise_unary(PrimIDs.EXP2, "exp2", supported=("float",))
expm1 = _make_elementwise_unary(PrimIDs.EXPM1, "expm1", supported=("float",))
floor = _make_elementwise_unary(PrimIDs.FLOOR, "floor", supported=("float",))
isfinite = _make_elementwise_unary(PrimIDs.ISFINITE, "isfinite", tpk=_K.ALWAYS_BOOL)
isinf = _make_elementwise_unary(PrimIDs.ISINF, "isinf", tpk=_K.ALWAYS_BOOL)
isnan = _make_elementwise_unary(PrimIDs.ISNAN, "isnan", tpk=_K.ALWAYS_BOOL)
lgamma = _make_elementwise_unary(PrimIDs.LGAMMA, "lgamma", supported=("float",))
log = _make_elementwise_unary(PrimIDs.LOG, "log", supported=_float_kinds)
log10 = _make_elementwise_unary(PrimIDs.LOG10, "log10", supported=("float",))
log1p = _make_elementwise_unary(PrimIDs.LOG1P, "log1p", supported=("float",))
log2 = _make_elementwise_unary(PrimIDs.LOG2, "log2", supported=("float",))
neg = _make_elementwise_unary(PrimIDs.NEG, "neg")
reciprocal = _make_elementwise_unary(PrimIDs.RECIPROCAL, "reciprocal", supported=_float_kinds)
round_prim = _make_elementwise_unary(PrimIDs.ROUND, "round", supported=("float",))
rsqrt = _make_elementwise_unary(PrimIDs.RSQRT, "rsqrt", supported=_float_kinds)
sign = _make_elementwise_unary(PrimIDs.SIGN, "sign")
signbit = _make_elementwise_unary(PrimIDs.SIGNBIT, "signbit", tpk=_K.ALWAYS_BOOL)
sin = _make_elementwise_unary(PrimIDs.SIN, "sin", supported=_float_kinds)
sinh = _make_elementwise_unary(PrimIDs.SINH, "sinh", supported=_float_kinds)
sqrt = _make_elementwise_unary(PrimIDs.SQRT, "sqrt", supported=_float_kinds)
tan = _make_elementwise_unary(PrimIDs.TAN, "tan", supported=_float_kinds)
tanh = _make_elementwise_unary(PrimIDs.TANH, "tanh", supported=_float_kinds)
trunc = _make_elementwise_unary(PrimIDs.TRUNC, "trunc", supported=("float",))
real = _make_elementwise_unary(PrimIDs.REAL, "real", tpk=_K.COMPLEX_TO_FLOAT, supported=_float_kinds)
imag = _make_elementwise_unary(PrimIDs.IMAG, "imag", tpk=_K.COMPLEX_TO_FLOAT, supported=("complex",))


def _elementwise_binary_meta_factory(name: str, *, type_promotion_kind):
    def meta(a, b):
        if isinstance(a, (Number, NumberProxy)) and isinstance(b, (Number, NumberProxy)):
            return _number_fold(name, a, b)
        check(
            isinstance(a, (TensorProxy, Number, NumberProxy)) and isinstance(b, (TensorProxy, Number, NumberProxy)),
            lambda: f"{name} expects tensors/numbers",
        )
        ta = a if isinstance(a, TensorProxy) else b
        if isinstance(a, TensorProxy) and isinstance(b, TensorProxy):
            utils.check_same_shape(a, b, op=name)
            utils.check_same_device(a, b, op=name)
            check(
                a.dtype == b.dtype,
                lambda: f"{name} prim requires same dtypes, got {a.dtype} and {b.dtype} (promote in clang)",
            )
        _, result_dtype = utils.elementwise_type_promotion(a, b, type_promotion_kind=type_promotion_kind)
        return TensorProxy(like=ta, dtype=result_dtype)

    return meta


def _make_elementwise_binary(id: PrimIDs, name: str, *, tpk=_K.PRESERVE) -> Symbol:
    return make_prim(
        id,
        name,
        _elementwise_binary_meta_factory(name, type_promotion_kind=tpk),
        tags=(OpTags.ELEMENTWISE_BINARY_OP,),
    )


add = _make_elementwise_binary(PrimIDs.ADD, "add")
atan2 = _make_elementwise_binary(PrimIDs.ATAN2, "atan2")
bitwise_and = _make_elementwise_binary(PrimIDs.BITWISE_AND, "bitwise_and")
bitwise_or = _make_elementwise_binary(PrimIDs.BITWISE_OR, "bitwise_or")
bitwise_xor = _make_elementwise_binary(PrimIDs.BITWISE_XOR, "bitwise_xor")
bitwise_left_shift = _make_elementwise_binary(PrimIDs.BITWISE_LEFT_SHIFT, "bitwise_left_shift")
bitwise_right_shift = _make_elementwise_binary(PrimIDs.BITWISE_RIGHT_SHIFT, "bitwise_right_shift")
div = _make_elementwise_binary(PrimIDs.DIV, "div")
eq = _make_elementwise_binary(PrimIDs.EQ, "eq", tpk=_K.ALWAYS_BOOL)
fmod = _make_elementwise_binary(PrimIDs.FMOD, "fmod")
ge = _make_elementwise_binary(PrimIDs.GE, "ge", tpk=_K.ALWAYS_BOOL)
gt = _make_elementwise_binary(PrimIDs.GT, "gt", tpk=_K.ALWAYS_BOOL)
le = _make_elementwise_binary(PrimIDs.LE, "le", tpk=_K.ALWAYS_BOOL)
lt = _make_elementwise_binary(PrimIDs.LT, "lt", tpk=_K.ALWAYS_BOOL)
maximum = _make_elementwise_binary(PrimIDs.MAXIMUM, "maximum")
minimum = _make_elementwise_binary(PrimIDs.MINIMUM, "minimum")
mul = _make_elementwise_binary(PrimIDs.MUL, "mul")
ne = _make_elementwise_binary(PrimIDs.NE, "ne", tpk=_K.ALWAYS_BOOL)
nextafter = _make_elementwise_binary(PrimIDs.NEXTAFTER, "nextafter")
pow_prim = _make_elementwise_binary(PrimIDs.POW, "pow")
remainder = _make_elementwise_binary(PrimIDs.REMAINDER, "remainder")
sub = _make_elementwise_binary(PrimIDs.SUB, "sub")
copysign = _make_elementwise_binary(PrimIDs.COPYSIGN, "copysign")
zeta = _make_elementwise_binary(PrimIDs.ZETA, "zeta")


def _polygamma_meta(n: int, a: TensorProxy) -> TensorProxy:
    check(isinstance(a, TensorProxy), "polygamma expects a tensor")
    check(dtypes.is_float_dtype(a.dtype), "polygamma requires a float tensor")
    return TensorProxy(like=a)


# No ELEMENTWISE_UNARY_OP tag: args[0] is an int order (not a tensor), and the
# op is expensive — remat's cheap-to-recompute heuristic must not claim it.
polygamma = make_prim(PrimIDs.POLYGAMMA, "polygamma", _polygamma_meta)


def _where_meta(pred, a, b):
    if isinstance(pred, TensorProxy):
        check(dtypes.is_boolean_dtype(pred.dtype), "where predicate must be boolean")
    ta = a if isinstance(a, TensorProxy) else (b if isinstance(b, TensorProxy) else pred)
    check(isinstance(ta, TensorProxy), "where prim requires at least one tensor input")
    shapes = [x.shape for x in (pred, a, b) if isinstance(x, TensorProxy)]
    first = shapes[0]
    check(all(tuple(s) == tuple(first) for s in shapes), "where prim requires same shapes (broadcast in clang)")
    _, result_dtype = utils.elementwise_type_promotion(a, b, type_promotion_kind=_K.PRESERVE)
    return TensorProxy(like=ta, shape=first, dtype=result_dtype)


where = make_prim(PrimIDs.WHERE, "where", _where_meta)


# =============================================================================
# Reductions
# =============================================================================


def _reduction_output_shape(shape: tuple, dims: tuple) -> tuple:
    return tuple(s for i, s in enumerate(shape) if i not in dims)


def _reduction_meta_factory(name: str, *, output_dtype_fn=None):
    def meta(a: TensorProxy, dims: Sequence[int]) -> TensorProxy:
        check(isinstance(a, TensorProxy), lambda: f"{name} expects a tensor")
        dims = canonicalize_dims(a.ndim, tuple(dims))
        utils.check_no_duplicates(dims)
        shape = _reduction_output_shape(a.shape, dims)
        out_dtype = output_dtype_fn(a) if output_dtype_fn is not None else a.dtype
        return TensorProxy(like=a, shape=shape, dtype=out_dtype)

    return meta


def _sum_dtype(a: TensorProxy) -> dtypes.dtype:
    # torch semantics: bool/int sums accumulate in int64
    if dtypes.is_exact_dtype(a.dtype):
        return dtypes.int64
    return a.dtype


amax = make_prim(PrimIDs.AMAX, "amax", _reduction_meta_factory("amax"), tags=(OpTags.REDUCTION_OP,))
amin = make_prim(PrimIDs.AMIN, "amin", _reduction_meta_factory("amin"), tags=(OpTags.REDUCTION_OP,))
prod = make_prim(PrimIDs.PROD, "prod", _reduction_meta_factory("prod", output_dtype_fn=_sum_dtype), tags=(OpTags.REDUCTION_OP,))
sum_prim = make_prim(PrimIDs.SUM, "sum", _reduction_meta_factory("sum", output_dtype_fn=_sum_dtype), tags=(OpTags.REDUCTION_OP,))


def _var_meta(a: TensorProxy, dims: Sequence[int], *, correction: Number) -> TensorProxy:
    check(dtypes.is_inexact_dtype(a.dtype), "var requires float/complex input")
    dims = canonicalize_dims(a.ndim, tuple(dims))
    shape = _reduction_output_shape(a.shape, dims)
    out_dtype = dtypes.corresponding_real_dtype(a.dtype)
    return TensorProxy(like=a, shape=shape, dtype=out_dtype)


var = make_prim(PrimIDs.VAR, "var", _var_meta, tags=(OpTags.REDUCTION_OP,))


def _var_mean_meta(a: TensorProxy, dims: Sequence[int], *, correction: Number) -> tuple:
    v = _var_meta(a, dims, correction=correction)
    dims_c = canonicalize_dims(a.ndim, tuple(dims))
    shape = _reduction_output_shape(a.shape, dims_c)
    m = TensorProxy(like=a, shape=shape)
    return v, m


var_mean = make_prim(PrimIDs.VAR_MEAN, "var_mean", _var_mean_meta, tags=(OpTags.REDUCTION_OP,))


def _argminmax_meta(a: TensorProxy, dim: Optional[int]) -> TensorProxy:
    if dim is None:
        return TensorProxy(like=a, shape=(), dtype=dtypes.int64)
    dim = canonicalize_dim(a.ndim, dim)
    shape = _reduction_output_shape(a.shape, (dim,))
    return TensorProxy(like=a, shape=shape, dtype=dtypes.int64)


argmax = make_prim(PrimIDs.ARGMAX, "argmax", _argminmax_meta, tags=(OpTags.REDUCTION_OP,))
argmin = make_prim(PrimIDs.ARGMIN, "argmin", _argminmax_meta, tags=(OpTags.REDUCTION_OP,))


# =============================================================================
# Linear algebra / NN prims
# =============================================================================


def _matmul_meta(a: TensorProxy, b: TensorProxy) -> TensorProxy:
    check(isinstance(a, TensorProxy) and isinstance(b, TensorProxy), "matmul expects tensors")
    check(a.ndim >= 1 and b.ndim >= 1, "matmul requires rank >= 1")
    check(a.dtype == b.dtype, lambda: f"matmul dtype mismatch {a.dtype} vs {b.dtype}")
    if a.ndim == 1 and b.ndim == 1:
        check(a.shape[0] == b.shape[0], "matmul contraction mismatch")
        return TensorProxy(like=a, shape=())
    if a.ndim == 1:
        check(a.shape[0] == b.shape[-2], "matmul contraction mismatch")
        return TensorProxy(like=b, shape=b.shape[:-2] + (b.shape[-1],))
    if b.ndim == 1:
        check(a.shape[-1] == b.shape[0], "matmul contraction mismatch")
        return TensorProxy(like=a, shape=a.shape[:-1])
    check(a.shape[-1] == b.shape[-2], lambda: f"matmul contraction mismatch {a.shape} @ {b.shape}")
    batch = compute_broadcast_shape(a.shape[:-2], b.shape[:-2])
    return TensorProxy(like=a, shape=batch + (a.shape[-2], b.shape[-1]))


matmul = make_prim(PrimIDs.MATMUL, "matmul", _matmul_meta, tags=(OpTags.MATMUL_OP,))


def _linear_meta(a: TensorProxy, w: TensorProxy, bias: Optional[TensorProxy]) -> TensorProxy:
    check(w.ndim == 2, "linear weight must be 2D (out_features, in_features)")
    check(a.shape[-1] == w.shape[1], lambda: f"linear: input {a.shape} vs weight {w.shape}")
    if bias is not None:
        check(bias.ndim == 1 and bias.shape[0] == w.shape[0], "linear bias shape mismatch")
    return TensorProxy(like=a, shape=a.shape[:-1] + (w.shape[0],))


linear = make_prim(PrimIDs.LINEAR, "linear", _linear_meta, tags=(OpTags.MATMUL_OP,))


def _convolution_meta(
    a: TensorProxy,
    weight: TensorProxy,
    bias: Optional[TensorProxy],
    stride: Sequence[int],
    padding: Sequence[int],
    dilation: Sequence[int],
    groups: int,
) -> TensorProxy:
    # a: (N, C_in, *spatial); weight: (C_out, C_in/groups, *kernel)
    check(a.ndim == weight.ndim, "convolution input/weight rank mismatch")
    spatial = a.ndim - 2
    check(spatial >= 1, "convolution requires at least one spatial dim")
    check(a.shape[1] == weight.shape[1] * groups, "convolution channel mismatch")
    out_spatial = []
    for i in range(spatial):
        s_in = a.shape[2 + i]
        k = weight.shape[2 + i]
        st = stride[i] if i < len(stride) else stride[-1]
        p = padding[i] if i < len(padding) else padding[-1]
        d = dilation[i] if i < len(dilation) else dilation[-1]
        out = (s_in + 2 * p - d * (k - 1) - 1) // st + 1
        out_spatial.append(out)
    return TensorProxy(like=a, shape=(a.shape[0], weight.shape[0], *out_spatial))


convolution = make_prim(PrimIDs.CONVOLUTION, "convolution", _convolution_meta, tags=(OpTags.MATMUL_OP,))


def _convolution_bwd_meta(
    g: TensorProxy,
    a: TensorProxy,
    weight: TensorProxy,
    stride: Sequence[int],
    padding: Sequence[int],
    dilation: Sequence[int],
    groups: int,
) -> tuple:
    """(d_input, d_weight) of `convolution` — lowered by jaxex to the
    transposed convolutions XLA compiles onto the MXU (reference seat: the
    torch conv backward ATen kernels)."""
    return TensorProxy(like=a), TensorProxy(like=weight)


convolution_bwd = make_prim(
    PrimIDs.CONVOLUTION_BWD, "convolution_bwd", _convolution_bwd_meta, tags=(OpTags.MATMUL_OP,)
)


def _embedding_meta(indices: TensorProxy, weight: TensorProxy) -> TensorProxy:
    check(weight.ndim == 2, "embedding weight must be 2D")
    check(dtypes.is_nonboolean_integer_dtype(indices.dtype), "embedding indices must be integer")
    return TensorProxy(like=weight, shape=indices.shape + (weight.shape[1],))


embedding = make_prim(PrimIDs.EMBEDDING, "embedding", _embedding_meta)


def _embedding_backward_meta(grad: TensorProxy, indices: TensorProxy, num_weights: int, embed_dim: int) -> TensorProxy:
    return TensorProxy(like=grad, shape=(num_weights, embed_dim))


embedding_backward = make_prim(PrimIDs.EMBEDDING_BACKWARD, "embedding_backward", _embedding_backward_meta)


def _pool_out_spatial(in_sizes, window, strides, padding):
    out = []
    for s, w, st, (lo, hi) in zip(in_sizes, window, strides, padding):
        out.append((s + lo + hi - w) // st + 1)
    return tuple(out)


def _pool_meta(
    a: TensorProxy, kind: str, window: Sequence[int], strides: Sequence[int],
    padding: Sequence[tuple],
) -> TensorProxy:
    """Window reduction over the trailing len(window) dims of (N, C, *spatial)
    input — lowers to XLA reduce_window, the native TPU pooling op
    (reference seat: the torch max/avg_poolNd ATen calls,
    thunder/torch/__init__.py max_pool1d..avg_pool3d)."""
    check(kind in ("max", "avg"), lambda: f"Unknown pool kind {kind}")
    k = len(window)
    check(a.ndim >= k + 1, "pool input rank too small for window")
    spatial = _pool_out_spatial(a.shape[-k:], window, strides, padding)
    return TensorProxy(like=a, shape=tuple(a.shape[:-k]) + spatial)


pool = make_prim(PrimIDs.POOL, "pool", _pool_meta, tags=(OpTags.REDUCTION_OP,))


def _pool_bwd_meta(g: TensorProxy, a: TensorProxy, kind: str, window, strides, padding) -> TensorProxy:
    return TensorProxy(like=a)


pool_bwd = make_prim(PrimIDs.POOL_BWD, "pool_bwd", _pool_bwd_meta)


def _uniform_philox_meta(
    shape: Sequence[int], minval: Number, maxval: Number, *, seed, offset,
    device: devices.Device, dtype: dtypes.dtype,
) -> TensorProxy:
    """Counter-based (stateless) uniform: same (seed, offset) → same bits
    (reference: thunder/core/prims.py `uniform_philox:142`). Pure given its
    args, so it stages under jit without the RNG functionalization pass."""
    check(dtypes.is_float_dtype(dtype), "uniform_philox requires a float dtype")
    return TensorProxy(shape=tuple(shape), device=devices.to_device(device), dtype=dtype)


uniform_philox = make_prim(PrimIDs.UNIFORM_PHILOX, "uniform_philox", _uniform_philox_meta)


# Generated code prints prims qualified as ``prims.<name>``.
from thunder_tpu.core.symbol import register_module as _register_module  # noqa: E402

_register_module("prims", __import__("sys").modules[__name__])
