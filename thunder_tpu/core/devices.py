"""Devices for the trace IR.

Reference parity: thunder/core/devices.py (`Device:84`, `DeviceType:14`). The
reference knows CPU/CUDA; this build is TPU-first: device types are CPU and
TPU, and a ``Device`` resolves to a concrete ``jax.Device``. Multi-device
placement is expressed through shardings (see thunder_tpu/parallel), not
through per-tensor device indices, so ``index`` mostly matters for CPU test
meshes.
"""

from __future__ import annotations

import enum
from typing import Any, Optional


class DeviceType(enum.Enum):
    CPU = enum.auto()
    TPU = enum.auto()
    # Recognized for frontend compatibility when importing CUDA-targeted
    # programs; mapped to the accelerator (TPU) at trace time.
    CUDA = enum.auto()


_devicetype_names = {DeviceType.CPU: "cpu", DeviceType.TPU: "tpu", DeviceType.CUDA: "cuda"}
_name_to_devicetype = {v: k for k, v in _devicetype_names.items()}


def devicetype_string(dt: DeviceType) -> str:
    return _devicetype_names[dt]


class Device:
    def __init__(self, string_or_type: Any = None, index: Optional[int] = None):
        if string_or_type is None:
            string_or_type = default_accelerator_type()
        if isinstance(string_or_type, Device):
            self.devicetype = string_or_type.devicetype
            self.index = string_or_type.index if index is None else index
            return
        if isinstance(string_or_type, DeviceType):
            self.devicetype = string_or_type
            self.index = 0 if index is None else index
            return
        if isinstance(string_or_type, str):
            name, _, idx = string_or_type.partition(":")
            devicetype = _name_to_devicetype.get(name)
            if devicetype is None:
                raise ValueError(f"Unknown device string {string_or_type!r}")
            self.devicetype = devicetype
            self.index = int(idx) if idx else (0 if index is None else index)
            return
        raise ValueError(f"Cannot construct Device from {string_or_type!r}")

    @property
    def type(self) -> str:
        return devicetype_string(self.devicetype)

    def __repr__(self) -> str:
        return f'devices.Device("{self.type}:{self.index}")'

    def __str__(self) -> str:
        return f"{self.type}:{self.index}"

    def __hash__(self) -> int:
        return hash((self.devicetype, self.index))

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, Device):
            return NotImplemented
        return self.devicetype == other.devicetype and self.index == other.index

    # -- jax resolution ------------------------------------------------------

    def jax_device(self):
        """Resolve to a concrete jax.Device (canonicalizing CUDA→accelerator)."""
        import jax

        if self.devicetype == DeviceType.CPU:
            return jax.devices("cpu")[self.index]
        devs = jax.devices()
        return devs[self.index % len(devs)]


def default_accelerator_type() -> DeviceType:
    import jax

    try:
        plat = jax.default_backend()
    except Exception:
        plat = "cpu"
    return DeviceType.CPU if plat == "cpu" else DeviceType.TPU


def to_device(x: Any) -> Optional[Device]:
    if x is None:
        return None
    if isinstance(x, Device):
        return x
    if isinstance(x, (str, DeviceType)):
        return Device(x)
    # torch.device / jax.Device duck-typing
    plat = getattr(x, "platform", None)
    if plat is not None:  # jax.Device
        name = "cpu" if plat == "cpu" else "tpu"
        return Device(name, getattr(x, "id", 0))
    typ = getattr(x, "type", None)
    if typ is not None:  # torch.device
        return Device(typ, getattr(x, "index", None) or 0)
    raise ValueError(f"Cannot convert {x!r} to a Device")


cpu = Device("cpu")
