"""Proxies: the abstract values that flow through traces.

Reference parity: thunder/core/proxies.py (`Proxy:91`, `NumberProxy:567`,
`TensorProxy:1147`, `FutureTensorProxy:1064`, `Variable`, `variableify:47`,
`DistParallelType` a.k.a. `DDPType:995`).

TPU-first differences:
- ``TensorProxy`` carries an optional ``sharding`` — a named-axis partition
  spec (tuple of mesh-axis names or None per dim) — so distributed transforms
  annotate placement directly in the IR and lowering emits GSPMD shardings
  rather than explicit NCCL calls.
- Devices are CPU/TPU; multi-chip placement is a property of the sharding,
  not of the device index.
"""

from __future__ import annotations

from numbers import Number
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core import baseutils, devices, dtypes
from thunder_tpu.core.baseutils import ProxyInterface, check
from thunder_tpu.core.langctxs import resolve_method


import enum


class DistParallelType(enum.Enum):
    """How a parameter is laid out across the data-parallel mesh axis.

    Reference parity: thunder/core/proxies.py `DDPType:995` (NONE / REPLICATED
    / FULLY_SHARDED), extended with COLUMN_WISE/ROW_WISE used by tensor
    parallelism (absent from the reference; first-class here).
    """

    NONE = enum.auto()
    REPLICATED = enum.auto()
    FULLY_SHARDED = enum.auto()
    COLUMN_WISE = enum.auto()
    ROW_WISE = enum.auto()


def _get_tracectx():
    from thunder_tpu.core.trace import get_tracectx

    return get_tracectx()


class Proxy(ProxyInterface):
    """Base class for all abstract trace values."""

    _counter_prefix = "p"

    def __init__(self, name: Optional[str] = None, *, prefix: Optional[str] = None):
        trace = _get_tracectx()
        if name is None:
            prefix = prefix if prefix is not None else self._counter_prefix
            if trace is not None:
                name = trace.make_name(prefix=prefix)
            else:
                name = f"{prefix}?"
        else:
            if trace is not None:
                trace.add_name(name)
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    def replace_name(self, name: str) -> "Proxy":
        """Return a copy of this proxy with a different name."""
        return self.__class__(name=name)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self._name}>"

    def type_string(self) -> str:
        return "Any"

    # Proxies are hashable by identity; Variable wraps them for by-name keys.
    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: Any) -> Any:
        return self is other


class Variable:
    """Hashable by-name wrapper over a proxy (reference: proxies.py:27)."""

    __slots__ = ("proxy",)

    def __init__(self, proxy: Proxy):
        self.proxy = proxy

    def __hash__(self) -> int:
        return hash(self.proxy._name)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Variable) and self.proxy._name == other.proxy._name

    def __repr__(self) -> str:
        return f"Variable({self.proxy._name})"


def variableify(x: Any) -> Any:
    return Variable(x) if isinstance(x, Proxy) else x


def unvariableify(x: Any) -> Any:
    return x.proxy if isinstance(x, Variable) else x


class AnyProxy(Proxy):
    """Wraps an opaque Python value observed during tracing."""

    _counter_prefix = "any"

    def __init__(self, value: Any = None, name: Optional[str] = None, prefix: Optional[str] = None):
        super().__init__(name, prefix=prefix)
        self.value = value

    def replace_name(self, name: str) -> "AnyProxy":
        return AnyProxy(self.value, name=name)


class StringProxy(Proxy):
    """A string input observed during tracing. Behaves like its value for
    comparison/containment so mode/reduction flags (``reduction == "mean"``,
    ``"->" in equation``) take the right branch instead of silently failing
    an identity comparison."""

    _counter_prefix = "s"

    def __init__(self, value: str, name: Optional[str] = None):
        super().__init__(name)
        self.value = value

    def replace_name(self, name: str) -> "StringProxy":
        return StringProxy(self.value, name=name)

    def __eq__(self, other) -> bool:
        return self.value == (other.value if isinstance(other, StringProxy) else other)

    def __hash__(self) -> int:
        return hash(self.value)

    def __str__(self) -> str:
        return self.value

    def __contains__(self, item) -> bool:
        return item in self.value

    def __iter__(self):
        return iter(self.value)

    def __len__(self) -> int:
        return len(self.value)


class CollectionProxy(Proxy):
    _counter_prefix = "C"

    def __init__(self, coll: Any, name: Optional[str] = None):
        super().__init__(name)
        self.coll = coll

    def replace_name(self, name: str) -> "CollectionProxy":
        return CollectionProxy(self.coll, name=name)


class NumberProxy(Proxy):
    """A Python number flowing through the trace.

    ``value`` is the concrete value observed while tracing (used for constant
    folding and CONSTANT_VALUES caching); ``python_type`` is bool/int/float/
    complex. Static by default — the cache guards on the value — matching the
    reference's default CONSTANT_VALUES cache mode.
    """

    _counter_prefix = "n"

    def __init__(
        self,
        value: Optional[Number] = None,
        name: Optional[str] = None,
        python_type: Optional[type] = None,
        prefix: Optional[str] = None,
    ):
        super().__init__(name, prefix=prefix or self._prefix_for(python_type))
        self.value = value
        self.python_type = python_type if python_type is not None else type(value)

    @staticmethod
    def _prefix_for(python_type: Optional[type]) -> str:
        return {bool: "b", int: "i", float: "f", complex: "c"}.get(python_type, "n")

    def replace_name(self, name: str) -> "NumberProxy":
        return NumberProxy(self.value, name=name, python_type=self.python_type)

    def type_string(self) -> str:
        return self.python_type.__name__

    @property
    def dtype(self) -> dtypes.dtype:
        return dtypes.numbertype_to_dtype(self.python_type)

    def known_value(self) -> bool:
        return self.value is not None

    def __index__(self) -> int:
        check(self.value is not None, "Cannot use an unknown NumberProxy as an index")
        return int(self.value)

    def __bool__(self) -> bool:
        check(
            self.value is not None,
            "Cannot branch on an unknown NumberProxy (data-dependent control flow)",
        )
        return bool(self.value)

    def __int__(self) -> int:
        check(self.value is not None, "Cannot concretize an unknown NumberProxy")
        return int(self.value)

    def __float__(self) -> float:
        check(self.value is not None, "Cannot concretize an unknown NumberProxy")
        return float(self.value)

    # Arithmetic dunders route through the active language so the ops are
    # recorded when symbolic-values mode arrives; with known values they
    # constant-fold at trace time.
    def _number_binop(self, other, op: Callable, name: str, *, reflected: bool = False):
        ovalue = other.value if isinstance(other, NumberProxy) else other
        if self.value is not None and ovalue is not None:
            return op(self.value, ovalue)
        method = resolve_method(name, self, other)
        if method is not None:
            # Reflected dunders (__radd__ etc.) mean `other OP self` — the
            # recorded op's operand order must match.
            return method(other, self) if reflected else method(self, other)
        raise RuntimeError(f"Cannot compute {name} on unknown numbers without a language method")

    def __add__(self, other):
        return self._number_binop(other, lambda a, b: a + b, "add")

    def __radd__(self, other):
        return self._number_binop(other, lambda a, b: b + a, "add", reflected=True)

    def __sub__(self, other):
        return self._number_binop(other, lambda a, b: a - b, "sub")

    def __rsub__(self, other):
        return self._number_binop(other, lambda a, b: b - a, "sub", reflected=True)

    def __mul__(self, other):
        return self._number_binop(other, lambda a, b: a * b, "mul")

    def __rmul__(self, other):
        return self._number_binop(other, lambda a, b: b * a, "mul", reflected=True)

    def __truediv__(self, other):
        return self._number_binop(other, lambda a, b: a / b, "true_divide")

    def __rtruediv__(self, other):
        return self._number_binop(other, lambda a, b: b / a, "true_divide", reflected=True)

    def __floordiv__(self, other):
        return self._number_binop(other, lambda a, b: a // b, "floor_divide")

    def __rfloordiv__(self, other):
        return self._number_binop(other, lambda a, b: b // a, "floor_divide", reflected=True)

    def __mod__(self, other):
        return self._number_binop(other, lambda a, b: a % b, "remainder")

    def __rmod__(self, other):
        return self._number_binop(other, lambda a, b: b % a, "remainder", reflected=True)

    def __pow__(self, other):
        return self._number_binop(other, lambda a, b: a**b, "pow")

    def __rpow__(self, other):
        return self._number_binop(other, lambda a, b: b**a, "pow", reflected=True)

    def __neg__(self):
        if self.value is not None:
            return -self.value
        return resolve_method("neg", self)(self)

    def __eq__(self, other):
        ovalue = other.value if isinstance(other, NumberProxy) else other
        if self.value is not None and (not isinstance(other, Proxy) or ovalue is not None):
            return self.value == ovalue
        return NotImplemented

    def __hash__(self) -> int:
        return id(self)

    def __lt__(self, other):
        return self._number_binop(other, lambda a, b: a < b, "lt")

    def __le__(self, other):
        return self._number_binop(other, lambda a, b: a <= b, "le")

    def __gt__(self, other):
        return self._number_binop(other, lambda a, b: a > b, "gt")

    def __ge__(self, other):
        return self._number_binop(other, lambda a, b: a >= b, "ge")


class IntegerProxy(NumberProxy):
    def __init__(self, value=None, name=None):
        super().__init__(value, name=name, python_type=int)


class FloatProxy(NumberProxy):
    def __init__(self, value=None, name=None):
        super().__init__(value, name=name, python_type=float)


class ComplexProxy(NumberProxy):
    def __init__(self, value=None, name=None):
        super().__init__(value, name=name, python_type=complex)


def pyval(x: Any) -> Any:
    """Concrete Python value of a (number/string) proxy or passthrough."""
    if isinstance(x, (NumberProxy, StringProxy, AnyProxy)):
        return x.value
    return x


def pytype(x: Any) -> type:
    if isinstance(x, NumberProxy):
        return x.python_type
    return type(x)


ShapeLike = Sequence[int]


def _lift_operand(x):
    """Concrete array operand of a proxy op -> baked tensor constant (only
    meaningful inside a trace; passthrough otherwise).

    NOT redundant with Symbol.__call__'s lifting: clang language methods are
    plain wrapper FUNCTIONS that run dtype promotion/broadcast logic before
    any Symbol is called (clang/__init__._elementwise_binary_wrapper), so a
    raw array must be lifted before dispatch reaches them; the torch
    language's methods are Symbols and simply see an already-lifted proxy.
    Both layers memoize through prims.tensor_constant's per-trace memo."""
    from thunder_tpu.executors import bridge

    if bridge.is_concrete_tensor(x):
        from thunder_tpu.core.trace import get_tracectx

        if get_tracectx() is not None:
            from thunder_tpu.core import prims

            return prims.tensor_constant(x)
    return x


class TensorProxy(Proxy):
    """The abstract tensor: shape, dtype, device, requires_grad, distributed
    layout, and (TPU-first) an optional named-axis sharding spec.

    Reference parity: thunder/core/proxies.py `TensorProxy:1147`.
    """

    _counter_prefix = "t"

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        shape: Optional[ShapeLike] = None,
        device: Optional[devices.Device] = None,
        dtype: Optional[dtypes.dtype] = None,
        requires_grad: bool = False,
        dist_parallel_type: DistParallelType = DistParallelType.NONE,
        sharding: Optional[tuple] = None,
        like: Optional["TensorProxy"] = None,
        prefix: Optional[str] = None,
    ):
        super().__init__(name, prefix=prefix)
        if like is not None:
            shape = shape if shape is not None else like.shape
            device = device if device is not None else like.device
            dtype = dtype if dtype is not None else like.dtype
            requires_grad = like.requires_grad if requires_grad is False else requires_grad
            if sharding is None:
                sharding = like.sharding
        check(shape is not None, "TensorProxy requires a shape")
        self._shape = tuple(int(s) if isinstance(s, Number) else s for s in shape)
        self._device = devices.to_device(device) if device is not None else devices.cpu
        self._dtype = dtypes.to_dtype(dtype, true_dtype=True) if dtype is not None else dtypes.float32
        self._requires_grad = requires_grad and dtypes.is_inexact_dtype(self._dtype)
        self.dist_parallel_type = dist_parallel_type
        self.sharding = tuple(sharding) if sharding is not None else None
        # The unsharded ("logical") shape when this proxy is a dim-0 shard of
        # a distributed parameter (reference: proxies.py thunder_fsdp_padding_size etc.)
        self.unsharded_shape: Optional[tuple] = None
        # Symbolic-values caching: {dim: (lo, hi, class_id)} for input dims
        # lifted to bucket guards — the extents in _shape are the bucket's
        # padded extents, and the prologue guards membership, not equality
        # (core/bucketing.py; set during acquisition by trace_program).
        self._symbolic_dims: Optional[dict] = None

    # -- metadata ------------------------------------------------------------

    @property
    def shape(self) -> tuple:
        return self._shape

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def device(self) -> devices.Device:
        return self._device

    @property
    def dtype(self) -> dtypes.dtype:
        return dtypes.to_strong(self._dtype)

    @property
    def true_dtype(self) -> dtypes.dtype:
        return self._dtype

    @property
    def requires_grad(self) -> bool:
        return self._requires_grad

    @property
    def numel(self) -> int:
        n = 1
        for s in self._shape:
            n *= int(s)
        return n

    @property
    def size_bytes(self) -> int:
        return self.numel * self.dtype.bytes

    def replace_name(self, name: str) -> "TensorProxy":
        return self.replace(name=name)

    @classmethod
    def __torch_function__(cls, func, types, args=(), kwargs=None):
        """torch-dispatch hook: makes torch's C++ argument parsers accept
        proxies in Tensor positions and routes the call to the ltorch mirror
        (the frontend seat of the reference's interpreter lookasides,
        thunder/core/jit_ext.py `general_jit_lookaside:871`)."""
        from thunder_tpu.frontend.dispatch import torch_dispatch

        return torch_dispatch(func, types, args, kwargs)

    def replace(self, name: Optional[str] = None, **changes) -> "TensorProxy":
        p = TensorProxy(
            name=name,
            shape=changes.get("shape", self._shape),
            device=changes.get("device", self._device),
            dtype=changes.get("dtype", self._dtype),
            requires_grad=changes.get("requires_grad", self._requires_grad),
            dist_parallel_type=changes.get("dist_parallel_type", self.dist_parallel_type),
            sharding=changes.get("sharding", self.sharding),
        )
        p.unsharded_shape = changes.get("unsharded_shape", self.unsharded_shape)
        p._symbolic_dims = changes.get("_symbolic_dims", self._symbolic_dims)
        return p

    def type_string(self) -> str:
        shard = "" if self.sharding is None else f" @{self.sharding}"
        return f'"{self.device}" {self.dtype.shortname}{list(self.shape)}{shard}'

    def __repr__(self) -> str:
        return f"<TensorProxy {self._name}: {self.type_string()}>"

    # -- python object protocol ---------------------------------------------

    def __len__(self) -> int:
        check(self.ndim > 0, "len() of a 0-d tensor")
        return int(self._shape[0])

    def size(self, dim: Optional[int] = None):
        if dim is None:
            return self.shape
        return self.shape[dim]

    def dim(self) -> int:
        return self.ndim

    def numel_(self) -> int:
        return self.numel

    def is_floating_point(self) -> bool:
        # torch.Tensor API used by HF's ModuleUtilsMixin.dtype (iterates
        # parameters — TensorProxies while swapped in during tracing).
        return dtypes.is_inexact_dtype(dtypes.to_dtype(self.dtype)) and not dtypes.is_complex_dtype(
            dtypes.to_dtype(self.dtype)
        )

    def is_complex(self) -> bool:
        return dtypes.is_complex_dtype(dtypes.to_dtype(self.dtype))

    def __bool__(self):
        return self._concretize("bool")

    def __int__(self):
        return self._concretize("int")

    def __float__(self):
        return self._concretize("float")

    def __index__(self):
        return self._concretize("int")

    def _concretize(self, kind: str):
        """Python-scalar coercion of a traced tensor: evaluated eagerly on
        the trace's concrete example inputs and protected by a cache value
        guard (core/concrete.py). Reference parity: the interpreter frontend
        runs such branches on real tensors (jit_ext.py) and constrains the
        cache via prologue guards."""
        from thunder_tpu.core.concrete import concretize_scalar

        val = concretize_scalar(self, kind)
        if val is not None:
            return val
        raise RuntimeError(
            f"Cannot {kind}() a traced tensor with no concrete value (data-dependent "
            "control flow in a detached trace); use lax-style control flow or mark "
            "the value static"
        )

    # -- method / operator dispatch via the active language ------------------

    def _dispatch(self, name: str, *args, **kwargs):
        # proxy <op> captured-concrete-array: lift the array to a baked
        # trace constant before language methods inspect dtypes (the
        # closure/global/default capture cases; prims.tensor_constant).
        args = tuple(_lift_operand(a) for a in args)
        method = resolve_method(name, self, *args, **kwargs)
        if method is None:
            raise AttributeError(f"No language method {name!r} for TensorProxy")
        return method(self, *args, **kwargs)

    def __getattr__(self, name: str):
        # Only called when normal lookup fails: resolve tensor methods
        # through the language context (reference: TensorProxy.__getattr__).
        if name.startswith("_"):
            raise AttributeError(name)
        method = resolve_method(name)
        if method is None:
            raise AttributeError(f"TensorProxy has no attribute or language method {name!r}")
        import functools

        return functools.partial(method, self)

    # arithmetic
    def __add__(self, other):
        return self._dispatch("add", other)

    def __radd__(self, other):
        other = _lift_operand(other)
        return resolve_method("add", other, self)(other, self)

    def __sub__(self, other):
        return self._dispatch("sub", other)

    def __rsub__(self, other):
        other = _lift_operand(other)
        return resolve_method("sub", other, self)(other, self)

    def __mul__(self, other):
        return self._dispatch("mul", other)

    def __rmul__(self, other):
        other = _lift_operand(other)
        return resolve_method("mul", other, self)(other, self)

    def __truediv__(self, other):
        return self._dispatch("true_divide", other)

    def __rtruediv__(self, other):
        other = _lift_operand(other)
        return resolve_method("true_divide", other, self)(other, self)

    def __floordiv__(self, other):
        return self._dispatch("floor_divide", other)

    def __mod__(self, other):
        return self._dispatch("remainder", other)

    def __pow__(self, other):
        return self._dispatch("pow", other)

    def __rpow__(self, other):
        other = _lift_operand(other)
        return resolve_method("pow", other, self)(other, self)

    def __matmul__(self, other):
        return self._dispatch("matmul", other)

    def __rmatmul__(self, other):
        other = _lift_operand(other)
        return resolve_method("matmul", other, self)(other, self)

    def __neg__(self):
        return self._dispatch("neg")

    def __abs__(self):
        return self._dispatch("abs")

    # comparisons
    def __eq__(self, other):
        return self._dispatch("eq", other)

    def __ne__(self, other):
        return self._dispatch("ne", other)

    def __lt__(self, other):
        return self._dispatch("lt", other)

    def __le__(self, other):
        return self._dispatch("le", other)

    def __gt__(self, other):
        return self._dispatch("gt", other)

    def __ge__(self, other):
        return self._dispatch("ge", other)

    def __hash__(self) -> int:
        return id(self)

    # logical
    def __and__(self, other):
        return self._dispatch("bitwise_and", other)

    def __or__(self, other):
        return self._dispatch("bitwise_or", other)

    def __xor__(self, other):
        return self._dispatch("bitwise_xor", other)

    def __invert__(self):
        return self._dispatch("bitwise_not")

    # indexing
    def __getitem__(self, key):
        return self._dispatch("getitem", key)

    def __setitem__(self, key, value):
        # In-place indexed write: functionalizes via the setitem_ language
        # method (out-of-place update + proxy forwarding).
        self._dispatch("setitem_", key, value)


class FutureTensorProxy(TensorProxy):
    """Result of an async collective; must be resolved by a ``wait`` prim.

    Reference parity: thunder/core/proxies.py `FutureTensorProxy:1064`. On
    TPU the executor lowers wait() to identity — XLA's latency-hiding
    scheduler provides the async overlap — but the IR keeps the future/wait
    structure so trace-level comm scheduling is expressible.
    """

    _counter_prefix = "fut"

    def replace_name(self, name: str) -> "FutureTensorProxy":
        p = FutureTensorProxy(
            name=name,
            shape=self._shape,
            device=self._device,
            dtype=self._dtype,
        )
        p.sharding = self.sharding
        return p


def is_proxy(x: Any) -> bool:
    return isinstance(x, Proxy)


def is_proxyable(x: Any) -> bool:
    return isinstance(x, Number) or _is_concrete_tensor(x)


def _is_concrete_tensor(x: Any) -> bool:
    import numpy as np

    if isinstance(x, np.ndarray):
        return True
    tname = type(x).__module__
    return tname.startswith("jax") and hasattr(x, "shape") and hasattr(x, "dtype")


def proxy(x: Any, *, name: Optional[str] = None) -> Any:
    """Wrap a concrete value in the appropriate proxy (reference: proxies.py `proxy`)."""
    if isinstance(x, Proxy):
        return x
    if isinstance(x, bool):
        return NumberProxy(x, name=name, python_type=bool)
    if isinstance(x, int):
        return IntegerProxy(x, name=name)
    if isinstance(x, float):
        return FloatProxy(x, name=name)
    if isinstance(x, complex):
        return ComplexProxy(x, name=name)
    if isinstance(x, str):
        return StringProxy(x, name=name)
    tp = tensorproxy_from_concrete(x, name=name)
    if tp is not None:
        return tp
    return AnyProxy(x, name=name)


def tensorproxy_from_concrete(x: Any, *, name: Optional[str] = None) -> Optional[TensorProxy]:
    """Build a TensorProxy describing a concrete jax array / numpy array /
    torch tensor (reference: proxies.py `tensorproxy:1496`)."""
    import numpy as np

    mod = type(x).__module__
    if isinstance(x, np.ndarray):
        # Host data is device_put to the default accelerator at execution, so
        # it traces as that device (keeps single-program traces on one device).
        return TensorProxy(name=name, shape=x.shape, device=devices.Device(), dtype=dtypes.from_jax_dtype(x.dtype))
    if mod.startswith("jax") and hasattr(x, "dtype") and hasattr(x, "shape"):
        try:
            plat = list(x.devices())[0].platform if hasattr(x, "devices") else "cpu"
        except Exception:
            plat = "cpu"
        dev = devices.Device("cpu" if plat == "cpu" else "tpu")
        return TensorProxy(name=name, shape=x.shape, device=dev, dtype=dtypes.from_jax_dtype(x.dtype))
    if mod.startswith("torch") and hasattr(x, "dtype") and hasattr(x, "layout"):
        dev = devices.Device() if x.device.type == "cpu" else devices.to_device(x.device)
        return TensorProxy(
            name=name,
            shape=tuple(x.shape),
            device=dev,
            dtype=dtypes.from_torch_dtype(x.dtype),
            requires_grad=bool(getattr(x, "requires_grad", False)),
        )
    return None
