"""Trace pattern matching for custom peephole transforms.

Reference parity: thunder/core/patterns.py (`bind_names:19`, `match_all:364`)
— a small combinator API for finding op sequences in a trace and rewriting
them, used to prototype fusion/peephole passes without writing a full
visitor.

A :class:`Pattern` is an ordered list of per-op predicates. ``match_all``
scans the trace's top-level bound symbols in program order and returns
non-overlapping :class:`Match` es; steps may be separated by unrelated ops
(``allow_gaps=True``, the default) as long as the later step consumes a
proxy produced by an earlier matched step when ``connected=True``.

Rewrites go through :func:`replace`, which splices replacement bound symbols
(built inside a fresh trace context so new proxies get unique names) over a
match and leaves everything else untouched. DCE afterwards cleans dangling
producers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence, Union

from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx


Predicate = Callable[[Any], bool]


def _to_pred(p: Union[Predicate, Any]) -> Predicate:
    """An op id (PrimIDs member / symbol-id string) or a callable predicate."""
    if callable(p) and not hasattr(p, "__self__"):
        # A plain callable predicate over the bound symbol.
        return p
    return lambda bsym, _id=p: bsym.sym.id == _id


@dataclass
class Match:
    """One pattern occurrence: the matched bound symbols (in program order),
    their trace indices, and name → bsym bindings."""

    bsyms: list
    indices: list
    bindings: dict = field(default_factory=dict)

    def __getitem__(self, name: str):
        return self.bindings[name]


class Pattern:
    """Ordered op-sequence pattern (reference: patterns.py).

    >>> p = Pattern().match(PrimIDs.MUL, "m").match(PrimIDs.ADD, "a")
    >>> for m in p.match_all(trace):
    ...     print(m["m"], m["a"])
    """

    def __init__(self):
        self._steps: list[tuple[Predicate, Optional[str]]] = []

    def match(self, op: Union[Predicate, Any], name: Optional[str] = None) -> "Pattern":
        """Append a step: ``op`` is a symbol id (e.g. ``PrimIDs.MUL``, the
        enum member, or a torchsymbol id string) or a predicate
        ``bsym -> bool``; ``name`` binds the matched bsym in the Match."""
        self._steps.append((_to_pred(op), name))
        return self

    def match_all(
        self,
        trace: TraceCtx,
        *,
        allow_gaps: bool = True,
        connected: bool = True,
    ) -> list[Match]:
        """All non-overlapping occurrences, scanning left to right.

        ``allow_gaps``: unrelated ops may sit between matched steps.
        ``connected``: each step after the first must consume at least one
        proxy produced by a previously matched step (the usual dataflow-chain
        pattern; set False for purely positional matching)."""
        bsyms = list(trace.bound_symbols)
        matches: list[Match] = []
        used: set[int] = set()
        i = 0
        while i < len(bsyms):
            m = self._try_from(bsyms, i, used, allow_gaps, connected)
            if m is not None:
                matches.append(m)
                used.update(m.indices)
                i = m.indices[0] + 1
            else:
                i += 1
        return matches

    def _try_from(self, bsyms, start, used, allow_gaps, connected) -> Optional[Match]:
        pred0, name0 = self._steps[0]
        if start in used or not pred0(bsyms[start]):
            return None
        matched = [bsyms[start]]
        indices = [start]
        bindings = {name0: bsyms[start]} if name0 else {}
        produced = {o.name for o in bsyms[start].flat_proxy_outs}
        j = start + 1
        for pred, name in self._steps[1:]:
            found = False
            while j < len(bsyms):
                b = bsyms[j]
                if j not in used and pred(b) and (
                    not connected
                    or any(a.name in produced for a in b.flat_proxy_args)
                ):
                    matched.append(b)
                    indices.append(j)
                    if name:
                        bindings[name] = b
                    produced |= {o.name for o in b.flat_proxy_outs}
                    j += 1
                    found = True
                    break
                if not allow_gaps:
                    return None
                j += 1
            if not found:
                return None
        return Match(matched, indices, bindings)


def replace(trace: TraceCtx, match: Match, builder: Callable[[Match], Any]) -> TraceCtx:
    """Rewrite one match: ``builder(match)`` runs inside a fresh trace scope
    and records replacement ops (it may call clang/prims/ltorch symbols); its
    recorded bound symbols are spliced in place of the match's first bsym and
    the remaining matched bsyms are dropped. The builder must end by mapping
    the old outputs — return a dict {old_proxy_name: new_proxy} and every
    downstream reference is swapped."""
    from thunder_tpu.core.proxies import Proxy, variableify
    from thunder_tpu.core.pytree import tree_flatten, tree_unflatten

    new_trace = from_trace(trace)
    recorded: list = []
    with tracectx(new_trace):
        new_trace.push_scope(recorded)
        out_map = builder(match) or {}
        new_trace.pop_scope()

    swap = dict(out_map)
    swap_map = {
        variableify(old_proxy): new for old_proxy, new in _proxy_pairs(trace, swap)
    }

    drop = set(match.indices[1:])
    first = match.indices[0]

    # Refuse unsafe rewrites: an op OUTSIDE the match consuming a matched
    # intermediate that the builder did not remap would reference an
    # undefined proxy after the splice (allow_gaps matches permit exactly
    # this shape).
    matched_set = set(match.indices)
    dropped_outs = {
        o.name
        for i in matched_set
        for o in trace.bound_symbols[i].flat_proxy_outs
        if o.name not in swap
    }
    surviving = [
        b for i, b in enumerate(trace.bound_symbols) if i not in matched_set
    ]
    # The builder's own recorded ops are spliced in too — they may also not
    # reference a dropped matched intermediate (its producer is gone).
    for bsym in list(surviving) + recorded:
        for a in bsym.flat_proxy_args:
            if a.name in dropped_outs:
                raise ValueError(
                    f"replace(): op {bsym.sym.name!r} consumes matched "
                    f"intermediate {a.name!r} whose producer is removed by the "
                    f"rewrite; have the builder return a mapping for it, use "
                    f"the match's original inputs, or match the consumer too"
                )
    flat_trace_out, _ = tree_flatten(trace.output)
    for p in flat_trace_out:
        if isinstance(p, Proxy) and p.name in dropped_outs:
            raise ValueError(
                f"replace(): trace output {p.name!r} is a matched intermediate "
                f"with no replacement mapping"
            )
    out_bsyms = []
    for i, bsym in enumerate(trace.bound_symbols):
        if i == first:
            out_bsyms.extend(recorded)
            continue
        if i in drop:
            continue
        if swap_map:
            bsym = bsym.from_bsym_swap_proxies(swap_map, skip_output=True)
        out_bsyms.append(bsym)
    new_trace.bound_symbols = out_bsyms

    # Outputs may reference replaced proxies.
    flat_out, spec = tree_flatten(new_trace.output)
    new_trace.output = tree_unflatten(
        spec, [swap.get(p.name, p) if isinstance(p, Proxy) else p for p in flat_out]
    )
    return new_trace


def _proxy_pairs(trace: TraceCtx, swap: dict):
    """(old_proxy, new_proxy) pairs for names in ``swap``, resolved from the
    trace's producers/args."""
    by_name = {}
    for a in trace.args:
        if hasattr(a, "name"):
            by_name[a.name] = a
    for b in trace.bound_symbols:
        for o in b.flat_proxy_outs:
            by_name[o.name] = o
    return [(by_name[n], p) for n, p in swap.items() if n in by_name]
