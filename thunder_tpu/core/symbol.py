"""Symbols and bound symbols: the instructions of the trace IR.

Reference parity: thunder/core/symbol.py (`Symbol:127`, `Symbol.__call__:226`,
`BoundSymbol:280`, `from_bsym_swap_proxies:345`, `rhs:506`,
`BoundSymbolRHS:631`).

A ``Symbol`` is a traceable operation: calling it while a trace is active
records a ``BoundSymbol``. Non-primitive symbols record their decomposition as
nested ``subsymbols`` — the multi-level IR that lets executors claim ops at
any level (a Pallas executor claims ``torch.scaled_dot_product_attention``
whole; the XLA executor claims the prims it decomposes into).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Hashable, Optional, Sequence

from thunder_tpu.core import baseutils, codeutils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.proxies import Proxy, TensorProxy, Variable, variableify
from thunder_tpu.core.pytree import tree_flatten, tree_unflatten


# Display-module registry: maps a symbol's short module label (e.g. "prims",
# "ltorch") to the module object bound into generated-code namespaces.
MODULE_REGISTRY: dict[str, Any] = {}


def register_module(label: str, module: Any) -> None:
    MODULE_REGISTRY[label] = module


def resolve_inplace(x: Any) -> Any:
    """Follow a proxy's in-place forwarding chain to its latest functional
    value. In-place torch ops (``x.add_(y)``) functionalize by computing the
    out-of-place result and pointing the stale proxy at it; every later
    consumer resolves through this (reference analogue: thunder's implicit
    functionalization — generated traces are SSA)."""
    nxt = getattr(x, "_inplace_forward", None)
    while nxt is not None:
        x = nxt
        nxt = getattr(x, "_inplace_forward", None)
    return x


def resolve_inplace_tree(tree: Any) -> Any:
    flat, spec = tree_flatten(tree)
    return tree_unflatten(spec, [resolve_inplace(x) for x in flat])


def _detach_tree(result):
    """stop_gradient over every tensor proxy in an op result (no_grad)."""
    from thunder_tpu.core import prims
    from thunder_tpu.core.baseutils import ProxyInterface

    def detach(x):
        if isinstance(x, ProxyInterface) and hasattr(x, "dtype") and hasattr(x, "shape"):
            return prims.stop_gradient(x)
        return x

    flat, spec = tree_flatten(result)
    return tree_unflatten(spec, [detach(x) for x in flat])


_is_concrete_tensor = None  # bound lazily: importing bridge at module load cycles


def _lift_captured_tensors(args: tuple, kwargs: dict):
    """Replace concrete arrays (numpy/torch/jax) in a traced op's operands
    with baked tensor-constant proxies (prims.tensor_constant). Shallow +
    one list/tuple level; single pass, no-op (no allocation) when nothing
    concrete is present — this sits on the tracing hot path."""
    global _is_concrete_tensor

    ict = _is_concrete_tensor
    if ict is None:
        from thunder_tpu.executors.bridge import is_concrete_tensor as ict

        _is_concrete_tensor = ict

    def lift(x):
        if ict(x):
            from thunder_tpu.core import prims

            return prims.tensor_constant(x)
        if isinstance(x, (list, tuple)) and any(ict(v) for v in x):
            from thunder_tpu.core import prims

            return type(x)(
                prims.tensor_constant(v) if ict(v) else v for v in x
            )
        return x

    new_args = None
    for i, a in enumerate(args):
        if ict(a) or (isinstance(a, (list, tuple)) and any(ict(v) for v in a)):
            if new_args is None:
                new_args = list(args)
            new_args[i] = lift(a)
    new_kwargs = None
    for k, v in kwargs.items():
        if ict(v) or (isinstance(v, (list, tuple)) and any(ict(u) for u in v)):
            if new_kwargs is None:
                new_kwargs = dict(kwargs)
            new_kwargs[k] = lift(v)
    if new_args is None and new_kwargs is None:
        return args, kwargs
    return (tuple(new_args) if new_args is not None else args,
            new_kwargs if new_kwargs is not None else kwargs)


class Symbol:
    def __init__(
        self,
        name: str,
        meta: Optional[Callable] = None,
        *,
        id: Optional[Any] = None,
        is_prim: bool = False,
        is_fusion: bool = False,
        tags: Optional[Sequence[Any]] = None,
        executor: Optional[Any] = None,
        python_impl: Optional[Callable] = None,
        python_printer: Optional[Callable] = None,
        module: Optional[str] = None,
        _bind_postprocess: Optional[Callable] = None,
    ):
        self.name = name
        self.meta = meta
        self.id = id if id is not None else name
        self.is_prim = is_prim
        self.is_fusion = is_fusion
        self.tags = tuple(tags) if tags else ()
        self.executor = executor
        self.python_impl = python_impl
        self.python_printer = python_printer
        self.module = module  # dotted module path for display, e.g. "prims", "ttorch"
        self._bind_postprocess = _bind_postprocess

    def __repr__(self) -> str:
        return f"[Symbol {self.qualname}]"

    @property
    def qualname(self) -> str:
        return f"{self.module}.{self.name}" if self.module else self.name

    def __call__(self, *args, **kwargs):
        from thunder_tpu.core.trace import get_tracectx

        trace = get_tracectx()
        if trace is None:
            # Eager escape hatch: outside tracing, run the concrete impl.
            if self.python_impl is not None:
                return self.python_impl(*args, **kwargs)
            if self.executor is not None:
                impl = self.executor.get_impl(self.id)
                if impl is not None:
                    return impl(*args, **kwargs)
            raise RuntimeError(
                f"Symbol {self.qualname} called outside a trace and has no concrete implementation"
            )

        check(self.meta is not None, lambda: f"Symbol {self.qualname} has no meta function")

        # Cheap flag check: only traces that saw an in-place op pay for the
        # per-call proxy remap (tracing latency is a product metric).
        if getattr(trace, "_inplace_seen", False):
            args, kwargs = resolve_inplace_tree((args, kwargs))

        # Concrete arrays reaching an op during tracing are CAPTURED
        # constants (closures, globals, defaults — the VM's provenance
        # cases, reference interpreter.py): lift them into the trace as
        # baked tensor constants. Shallow + one container level covers the
        # real call shapes (cat/stack lists); deeper nesting reaches a meta
        # and fails loudly there.
        args, kwargs = _lift_captured_tensors(args, kwargs)

        if self.is_prim:
            result = self.meta(*args, **kwargs)
            subsymbols = ()
        else:
            subsymbols = []
            trace.push_scope(subsymbols)
            try:
                result = self.meta(*args, **kwargs)
            finally:
                trace.pop_scope()

        bsym = self.bind(*args, output=result, subsymbols=tuple(subsymbols), **kwargs)
        trace.add_bound_symbol(bsym)

        # torch.no_grad during acquisition (frontend/sharp.py toggles the
        # flag): detach this op's tensor outputs so nothing computed under
        # the block contributes gradients — applied at the TOP scope only
        # (composites wrap once, their subsymbols don't).
        from thunder_tpu.core.trace import _grad_mode_ctx

        if (
            not _grad_mode_ctx.get()
            and self.name != "stop_gradient"
            and len(trace._scopes) == 1
        ):
            result = _detach_tree(result)
        return result

    def bind(self, *args, output: Any, subsymbols: tuple = (), **kwargs) -> "BoundSymbol":
        bsym = BoundSymbol(self, args=args, kwargs=kwargs, output=output, subsymbols=subsymbols)
        if self._bind_postprocess is not None:
            self._bind_postprocess(bsym)
        return bsym


@dataclass(frozen=True)
class BoundSymbolRHS:
    """Hashable (symbol, args-with-variables) key for CSE (reference: symbol.py:631)."""

    sym_id: Hashable
    args: tuple
    kwargs: tuple

    def __hash__(self) -> int:
        try:
            return hash((self.sym_id, self.args, self.kwargs))
        except TypeError:
            return hash(self.sym_id)


class BoundSymbol(baseutils.BoundSymbolInterface):
    def __init__(
        self,
        sym: Symbol,
        args: tuple,
        kwargs: dict,
        output: Any,
        subsymbols: tuple = (),
    ):
        self.sym = sym
        self.args = tuple(args)
        self.kwargs = dict(kwargs)
        self.output = output
        self.subsymbols = tuple(subsymbols)
        # Objects the generated line needs bound into the exec namespace,
        # e.g. a compiled XLA region callable (reference: _call_ctx).
        self._call_ctx: dict[str, Any] = {}
        self.header: str = ""

    # -- tags ----------------------------------------------------------------

    def has_tag(self, tag: Any) -> bool:
        return tag in self.sym.tags

    # -- flattening ----------------------------------------------------------

    @property
    def flat_args(self) -> list:
        flat, _ = tree_flatten((self.args, self.kwargs))
        return flat

    @property
    def flat_proxy_args(self) -> list:
        return [a for a in self.flat_args if isinstance(a, Proxy)]

    @property
    def flat_outs(self) -> list:
        flat, _ = tree_flatten(self.output)
        return flat

    @property
    def flat_proxy_outs(self) -> list:
        return [o for o in self.flat_outs if isinstance(o, Proxy)]

    def _var_set(self, proxies) -> set:
        return {variableify(p) for p in proxies}

    # -- identity / CSE ------------------------------------------------------

    @property
    def rhs(self) -> BoundSymbolRHS:
        def keyify(x):
            if isinstance(x, Proxy):
                return Variable(x)
            return baseutils.make_hashable(x) if baseutils.is_collection(x) else x

        # The tree structure must be part of the key: None is an EMPTY
        # subtree to jax pytrees, so flattening alone maps e.g. the index
        # keys (None, None, :, None) and (None, None, None, :) to the same
        # leaves — and CSE would silently merge different ops.
        flat_args, spec_a = tree_flatten(self.args)
        flat_kwargs, spec_k = tree_flatten(tuple(sorted(self.kwargs.items())))
        return BoundSymbolRHS(
            self.sym.id,
            (str(spec_a),) + tuple(keyify(a) for a in flat_args),
            (str(spec_k),) + tuple(keyify(a) for a in flat_kwargs),
        )

    # -- rewriting -----------------------------------------------------------

    def from_bsym(self, *, sym=None, args=None, kwargs=None, output=None, subsymbols=None) -> "BoundSymbol":
        new = BoundSymbol(
            sym if sym is not None else self.sym,
            args=args if args is not None else self.args,
            kwargs=kwargs if kwargs is not None else self.kwargs,
            output=output if output is not None else self.output,
            subsymbols=subsymbols if subsymbols is not None else self.subsymbols,
        )
        new._call_ctx = dict(self._call_ctx)
        new.header = self.header
        return new

    def from_bsym_swap_proxies(self, swap_map: dict, skip_output: bool = False) -> "BoundSymbol":
        """Replace proxies by name per ``swap_map`` (Variable → proxy).

        Reference parity: symbol.py `from_bsym_swap_proxies:345` — load-bearing
        for the fw/bw split and remat passes.
        """
        if not swap_map:
            return self

        def swap(x):
            if isinstance(x, Proxy):
                return swap_map.get(variableify(x), x)
            return x

        def swap_tree(tree):
            flat, spec = tree_flatten(tree)
            return tree_unflatten(spec, [swap(x) for x in flat])

        new_args = swap_tree(self.args)
        new_kwargs = swap_tree(self.kwargs)
        new_output = self.output if skip_output else swap_tree(self.output)
        new_subsymbols = tuple(
            sub.from_bsym_swap_proxies(swap_map, skip_output=skip_output) for sub in self.subsymbols
        )
        return self.from_bsym(args=new_args, kwargs=new_kwargs, output=new_output, subsymbols=new_subsymbols)

    # -- codegen -------------------------------------------------------------

    def gen_call_target(self) -> tuple[str, Any]:
        """(name, callable) to bind in the exec namespace for this line.

        Claimed symbols print as ``<executor>_<name>`` bound to the executor
        impl; unclaimed symbols print qualified by their module
        (``prims.add``), with the module object bound in the namespace —
        matching the reference's generated-code style.
        """
        if self.sym.executor is not None:
            impl = self.sym.executor.get_impl(self.sym.id)
            if impl is not None:
                return f"{self.sym.executor.name}_{self.sym.name}", impl
        if self.sym.module is not None:
            mod = MODULE_REGISTRY.get(self.sym.module)
            if mod is not None:
                return f"{self.sym.module}.{self.sym.name}", (self.sym.module, mod)
        if self.sym.python_impl is not None:
            return self.sym.name, self.sym.python_impl
        return self.sym.name, self.sym

    def python(self, indent: int = 0, print_depth: int = 1) -> list[str]:
        lines = []
        pad = baseutils.indent(indent)
        if self.header:
            for hline in self.header.splitlines():
                lines.append(f"{pad}# {hline}")

        if self.sym.python_printer is not None:
            printed = self.sym.python_printer(self)
            for pline in printed if isinstance(printed, (list, tuple)) else [printed]:
                lines.append(f"{pad}{pline}")
            return lines

        ctx_name, _ = self.gen_call_target()
        arg_strs = [codeutils.prettyprint(a) for a in self.args]
        kwarg_strs = [f"{k}={codeutils.prettyprint(v)}" for k, v in self.kwargs.items()]
        call = f"{ctx_name}({', '.join(arg_strs + kwarg_strs)})"

        outs = self.flat_proxy_outs
        if self.output is None or not outs:
            line = f"{pad}{call}"
        else:
            out_str = codeutils.prettyprint(self.output)
            line = f"{pad}{out_str} = {call}"
        lines.append(line)

        if print_depth > 1 or (print_depth == -1):
            next_depth = -1 if print_depth == -1 else print_depth - 1
            for sub in self.subsymbols:
                for sline in sub.python(indent + 1, next_depth):
                    lines.append("# " + sline if False else sline)
        return lines

    def one_line(self) -> str:
        """The generated line(s) of this bound symbol collapsed to one
        string — the canonical "offending trace line" rendering shared by
        verifier diagnostics (analysis/diagnostics.py) and instrumentation
        attribution (observability/instrument.py)."""
        return "; ".join(s.strip() for s in self.python(indent=0))

    def __repr__(self) -> str:
        return "\n".join(self.python(0, print_depth=1))
