"""Meta-function utilities: type promotion, broadcasting, shape checks.

Reference parity: thunder/core/utils.py (type-promotion helpers `:351-483`,
`check_same_device`, canonicalize helpers). Promotion implements torch's
number/tensor semantics — weak (Python-number) dtypes only bump the kind,
never the width — because the torch-facing frontend must reproduce torch
numerics on TPU.
"""

from __future__ import annotations

import enum
from numbers import Number
from typing import Any, Optional, Sequence

from thunder_tpu.core import dtypes, devices
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.proxies import NumberProxy, TensorProxy, pyval, pytype


# -- dtype promotion ---------------------------------------------------------


class ELEMENTWISE_TYPE_PROMOTION_KIND(enum.Enum):
    DEFAULT = enum.auto()
    PRESERVE = enum.auto()
    INT_TO_FLOAT = enum.auto()
    ALWAYS_BOOL = enum.auto()
    COMPLEX_TO_FLOAT = enum.auto()
    BOOL_TO_LONG = enum.auto()


_KIND_ORDER = {"bool": 0, "uint": 1, "int": 1, "float": 2, "complex": 3}

_int_widths = [dtypes.uint8, dtypes.int8, dtypes.int16, dtypes.int32, dtypes.int64]
_float_widths = [dtypes.float8_e4m3, dtypes.float8_e5m2, dtypes.float16, dtypes.bfloat16, dtypes.float32, dtypes.float64]


def _wider(a: dtypes.dtype, b: dtypes.dtype) -> dtypes.dtype:
    """Widest of two same-kind dtypes, with torch pairing rules for mixed
    sub-byte/half types (f16+bf16 → f32; u8+i8 → i16)."""
    if a == b:
        return a
    ka, kb = a.kind, b.kind
    if ka in ("int", "uint") and kb in ("int", "uint"):
        if {a, b} == {dtypes.uint8, dtypes.int8}:
            return dtypes.int16
        return a if a.bytes >= b.bytes else b
    if ka == "float" and kb == "float":
        pair = {a, b}
        if pair == {dtypes.float16, dtypes.bfloat16}:
            return dtypes.float32
        if dtypes.float8_e4m3 in pair or dtypes.float8_e5m2 in pair:
            if pair == {dtypes.float8_e4m3, dtypes.float8_e5m2}:
                return dtypes.float16
            other = (pair - {dtypes.float8_e4m3, dtypes.float8_e5m2}).pop()
            return other
        return a if a.bytes >= b.bytes else b
    if ka == "complex" and kb == "complex":
        return a if a.bytes >= b.bytes else b
    raise AssertionError(f"_wider on mixed kinds {a} {b}")


_default_for_kind = {
    "bool": dtypes.bool8,
    "int": dtypes.int64,
    "uint": dtypes.int64,
    "float": dtypes.float32,
    "complex": dtypes.complex64,
}


def dtype_of(x: Any) -> dtypes.dtype:
    """True (possibly weak) dtype of a tensor proxy, number proxy, or number."""
    if isinstance(x, TensorProxy):
        return x.true_dtype
    if isinstance(x, NumberProxy):
        return dtypes.numbertype_to_dtype(x.python_type)
    if isinstance(x, Number):
        return dtypes.numbertype_to_dtype(type(x) if not isinstance(x, bool) else bool)
    raise ValueError(f"No dtype for {x!r}")


def elementwise_type_promotion(
    *args: Any, type_promotion_kind: ELEMENTWISE_TYPE_PROMOTION_KIND = ELEMENTWISE_TYPE_PROMOTION_KIND.DEFAULT
) -> tuple[dtypes.dtype, dtypes.dtype]:
    """(computation_dtype, result_dtype) for an elementwise op over ``args``.

    Reference parity: thunder/core/utils.py:351-483. Tensor (strong) dtypes
    dominate number (weak) dtypes of lower-or-equal kind; a number of a
    strictly higher kind bumps the result to the default dtype of that kind.
    """
    check(len(args) > 0, "Type promotion needs at least one argument")

    strong: Optional[dtypes.dtype] = None
    weak: Optional[dtypes.dtype] = None
    for a in args:
        d = dtype_of(a)
        if isinstance(a, TensorProxy):
            s = dtypes.to_strong(d)
            if strong is None:
                strong = s
            else:
                if _KIND_ORDER[s.kind] > _KIND_ORDER[strong.kind]:
                    strong = s
                elif _KIND_ORDER[s.kind] == _KIND_ORDER[strong.kind]:
                    strong = _wider(strong, s)
        else:
            s = dtypes.to_strong(d)
            if weak is None or _KIND_ORDER[s.kind] > _KIND_ORDER[weak.kind]:
                weak = s

    if strong is not None:
        if weak is not None and _KIND_ORDER[weak.kind] > _KIND_ORDER[strong.kind]:
            result = _default_for_kind[weak.kind]
        else:
            result = strong
    else:
        result = _default_for_kind[weak.kind]

    k = type_promotion_kind
    K = ELEMENTWISE_TYPE_PROMOTION_KIND
    if k is K.ALWAYS_BOOL:
        return result, dtypes.bool8
    if k is K.INT_TO_FLOAT and dtypes.is_exact_dtype(result):
        return dtypes.float32, dtypes.float32
    if k is K.COMPLEX_TO_FLOAT and dtypes.is_complex_dtype(result):
        return result, dtypes.corresponding_real_dtype(result)
    if k is K.BOOL_TO_LONG and dtypes.is_boolean_dtype(result):
        return dtypes.int64, dtypes.int64
    # Low-precision floats compute in themselves on TPU (bf16 is native on the
    # MXU/VPU); XLA upcasts internally where needed.
    return result, result


def get_numberlike_value(x: Any) -> Any:
    return pyval(x)


# -- shapes ------------------------------------------------------------------


def same_shape(a: Sequence[int], b: Sequence[int]) -> bool:
    return tuple(a) == tuple(b)


def check_same_shape(*args, op: str = "op") -> None:
    shapes = [tuple(a.shape) for a in args if isinstance(a, TensorProxy)]
    if shapes:
        first = shapes[0]
        check(all(s == first for s in shapes), lambda: f"{op}: mismatched shapes {shapes}")


def compute_broadcast_shape(*shapes: Optional[Sequence[int]]) -> tuple:
    """NumPy/torch broadcast rule over any number of shapes."""
    real = [tuple(s) for s in shapes if s is not None]
    if not real:
        return ()
    ndim = max(len(s) for s in real)
    out = []
    for i in range(ndim):
        dim = 1
        for s in real:
            idx = len(s) - ndim + i
            if idx < 0:
                continue
            d = s[idx]
            if d == 1:
                continue
            check(dim == 1 or dim == d, lambda: f"Cannot broadcast shapes {real}")
            dim = d
        out.append(dim)
    return tuple(out)


def canonicalize_dim(ndim: int, dim: int, wrap_scalar: bool = True) -> int:
    rng = ndim if ndim > 0 else (1 if wrap_scalar else 0)
    check(-rng <= dim < rng, lambda: f"Dimension {dim} out of range for rank {ndim}")
    return dim if dim >= 0 else dim + rng


def canonicalize_dims(ndim: int, dims: Sequence[int] | int) -> tuple:
    if isinstance(dims, int):
        return (canonicalize_dim(ndim, dims),)
    return tuple(canonicalize_dim(ndim, d) for d in dims)


def check_valid_permutation(ndim: int, perm: Sequence[int]) -> None:
    check(sorted(perm) == list(range(ndim)), lambda: f"Invalid permutation {perm} for rank {ndim}")


def check_no_duplicates(dims: Sequence[int]) -> None:
    check(len(set(dims)) == len(dims), lambda: f"Duplicate dims in {dims}")


# -- devices -----------------------------------------------------------------


def check_same_device(*args, op: str = "op") -> None:
    devs = [a.device for a in args if isinstance(a, TensorProxy)]
    if devs:
        first = devs[0]
        check(
            all(d == first for d in devs),
            lambda: f"{op}: tensors on different devices {devs}",
        )


def common_device(*args) -> devices.Device:
    for a in args:
        if isinstance(a, TensorProxy):
            return a.device
    return devices.cpu


# -- misc --------------------------------------------------------------------


class OrderedSet:
    """Insertion-ordered set (dict-backed)."""

    def __init__(self, items=()):
        self._d = dict.fromkeys(items)

    def add(self, x):
        self._d[x] = None

    def update(self, items):
        for x in items:
            self.add(x)

    def discard(self, x):
        self._d.pop(x, None)

    def remove(self, x):
        del self._d[x]

    def __contains__(self, x):
        return x in self._d

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def __bool__(self):
        return bool(self._d)


class ProxyDict:
    """Dict keyed by proxy name (reference: thunder/core/utils.py ProxyDict)."""

    def __init__(self):
        self._d: dict[str, Any] = {}

    def __setitem__(self, p, v):
        self._d[p.name] = v

    def __getitem__(self, p):
        return self._d[p.name]

    def __contains__(self, p):
        return p.name in self._d

    def get(self, p, default=None):
        return self._d.get(p.name, default)

    def setdefault(self, p, default):
        return self._d.setdefault(p.name, default)


def producers(bsyms) -> dict:
    """Variable → producing BoundSymbol."""
    from thunder_tpu.core.proxies import variableify

    out = {}
    for bsym in bsyms:
        for o in bsym.flat_proxy_outs:
            out.setdefault(variableify(o), bsym)
    return out


def consumers(bsyms) -> dict:
    """Variable → list of consuming BoundSymbols."""
    from thunder_tpu.core.proxies import variableify

    out = {}
    for bsym in bsyms:
        for a in bsym.flat_proxy_args:
            out.setdefault(variableify(a), []).append(bsym)
    return out
