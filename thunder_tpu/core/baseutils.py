"""Shared low-level utilities (reference parity: thunder/core/baseutils.py).

Holds the check helpers used by meta functions, the interface tags used by
codegen, and ``compile_and_exec`` used to turn generated Python source into a
callable.
"""

from __future__ import annotations

import linecache
from numbers import Number
from typing import Any, Callable, Hashable, Sequence, Type


class BoundSymbolInterface:
    pass


class ProxyInterface:
    pass


class SymbolInterface:
    pass


class TraceInterface:
    pass


class TagBase:
    pass


class GuardFailure(AssertionError):
    """Raised by prologue CHECK_* prims when a cached entry's guards do not
    match the current inputs. The cache probe loop catches exactly this type
    (reference parity: thunder/__init__.py:409-447 treats guard failure as the
    controlled cache-miss signal); any other exception from a prologue is a
    genuine bug and propagates."""


def check(pred: bool, msg: Callable[[], str] | str, exception_type: Type[Exception] = RuntimeError) -> None:
    """Raise ``exception_type`` with ``msg`` if ``pred`` is falsy. ``msg`` may
    be a thunk so message construction is free on the happy path."""
    if not pred:
        raise exception_type(msg() if callable(msg) else msg)


def check_type(x: Any, types: type | tuple[type, ...], name: str = "value") -> None:
    check(
        isinstance(x, types),
        lambda: f"Expected {name} to be of type {types}, got {type(x)}",
        ValueError,
    )


def check_types(xs: Sequence[Any], types: type | tuple[type, ...]) -> None:
    for x in xs:
        check_type(x, types)


def is_base_printable(x: Any) -> bool:
    from thunder_tpu.core import dtypes, devices

    if isinstance(x, (str, type(None), Number, slice, type(Ellipsis), dtypes.dtype, devices.Device)):
        return True
    if isinstance(x, (tuple, list)):
        return all(is_base_printable(v) for v in x)
    if isinstance(x, dict):
        return all(isinstance(k, (str, int)) and is_base_printable(v) for k, v in x.items())
    return False


def is_collection(x: Any) -> bool:
    return isinstance(x, (tuple, list, dict, set))


def sequencify(x: Any) -> Sequence:
    if x is None:
        return ()
    if isinstance(x, (tuple, list)):
        return x
    return (x,)


_exec_counter = 0


def compile_and_exec(name: str, source: str, ctx: dict[str, Any]) -> Callable:
    """Compile generated Python source and return the named function.

    Reference parity: thunder/core/baseutils.py's build-and-exec used by
    TraceCtx.python_callable (thunder/core/trace.py:400). The source is
    registered with ``linecache`` so tracebacks and ``inspect.getsource``
    resolve into the generated program — the generated trace being readable
    and debuggable is a core product feature.
    """
    global _exec_counter
    _exec_counter += 1
    filename = f"<thunder_tpu.gen {name}_{_exec_counter}>"
    lines = source.splitlines(keepends=True)
    linecache.cache[filename] = (len(source), None, lines, filename)
    code = compile(source, filename, "exec")
    namespace = dict(ctx)
    exec(code, namespace)
    fn = namespace[name]
    fn.__thunder_source__ = source
    return fn


def indent(level: int) -> str:
    return "  " * level


class NamedCounter:
    """Monotonic counters keyed by prefix, for name generation."""

    def __init__(self):
        self._counts: dict[str, int] = {}

    def next(self, prefix: str) -> int:
        n = self._counts.get(prefix, 0)
        self._counts[prefix] = n + 1
        return n


def make_hashable(x: Any) -> Hashable:
    if isinstance(x, (tuple, list)):
        return tuple(make_hashable(v) for v in x)
    if isinstance(x, dict):
        return tuple(sorted((k, make_hashable(v)) for k, v in x.items()))
    if isinstance(x, set):
        return frozenset(make_hashable(v) for v in x)
    return x
