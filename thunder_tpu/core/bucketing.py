"""Shape-bucketing policy for symbolic-values caching.

Under ``cache="symbolic values"`` a marked tensor dim is guarded by BUCKET
membership instead of its exact extent: the prologue checks ``lo < d <= hi``
and the dispatcher pads the dim up to ``hi``, so one trace + one XLA
executable serves every extent in the bucket (the standard answer to
recompile storms under variable batch/sequence traffic — see docs/caching.md).

Default policy (the serving-oriented TPU convention):

- dim 0 ("batch"): powers of two — extent n lands in ``(p/2, p]`` for the
  next power of two p;
- dim 1 ("seq"):   multiples of 128 — the TPU lane width, so padded
  sequences stay tile-aligned;
- dims >= 2 ("other"): exact — a varying feature dim recompiles per extent
  (padding a reduced-over feature dim is unsound without full masking).

Knobs: the ``THUNDER_TPU_BUCKETS`` environment variable and the ``buckets=``
jit option, e.g. ``THUNDER_TPU_BUCKETS="batch=pow2,seq=64,other=exact"`` or
``jit(fn, cache="symbolic values", buckets={"seq": 64})``. A rule is either
``"pow2"``, ``"exact"``, or a positive integer m (buckets are multiples of m).
"""

from __future__ import annotations

import os
from typing import Any, Optional


_RULE_NAMES = ("batch", "seq", "other")


def _validate_rule(rule: Any) -> Any:
    if rule in ("pow2", "exact"):
        return rule
    try:
        m = int(rule)
    except (TypeError, ValueError):
        raise ValueError(
            f"Invalid bucket rule {rule!r}: expected 'pow2', 'exact', or a positive integer"
        )
    if m <= 0:
        raise ValueError(f"Invalid bucket multiple {m}: must be positive")
    return m


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class BucketPolicy:
    """Maps (dim index, observed extent) -> the half-open bucket ``(lo, hi]``."""

    def __init__(self, batch: Any = "pow2", seq: Any = 128, other: Any = "exact"):
        self.batch = _validate_rule(batch)
        self.seq = _validate_rule(seq)
        self.other = _validate_rule(other)

    @classmethod
    def resolve(cls, option: Optional[dict] = None) -> "BucketPolicy":
        """Defaults <- THUNDER_TPU_BUCKETS env <- per-jit ``buckets=`` dict."""
        rules: dict[str, Any] = {}
        env = os.environ.get("THUNDER_TPU_BUCKETS", "").strip()
        if env:
            for part in env.split(","):
                if not part.strip():
                    continue
                k, _, v = part.partition("=")
                k = k.strip()
                if k not in _RULE_NAMES:
                    raise ValueError(
                        f"THUNDER_TPU_BUCKETS: unknown rule name {k!r} (expected one of {_RULE_NAMES})"
                    )
                rules[k] = v.strip()
        if option:
            for k, v in option.items():
                if k not in _RULE_NAMES:
                    raise ValueError(
                        f"buckets: unknown rule name {k!r} (expected one of {_RULE_NAMES})"
                    )
                rules[k] = v
        return cls(**{k: rules[k] for k in rules})

    def rule_for(self, dim: int) -> Any:
        if dim == 0:
            return self.batch
        if dim == 1:
            return self.seq
        return self.other

    def bucket(self, dim: int, extent: int) -> tuple[int, int]:
        """The bucket ``(lo, hi]`` containing ``extent`` for dim ``dim``.
        An empty dim (extent 0) opens its bucket downward (``lo = -1``) so
        the ``lo < d`` guard admits it."""
        rule = self.rule_for(dim)
        extent = int(extent)
        if rule == "exact":
            lo, hi = extent - 1, extent
        elif rule == "pow2":
            hi = _next_pow2(max(extent, 1))
            lo = hi // 2 if hi > 1 else 0
        else:
            m = int(rule)
            hi = -(-extent // m) * m if extent > 0 else m
            lo = hi - m
        if extent == 0:
            lo = -1
        return lo, hi

    def __repr__(self) -> str:
        return f"BucketPolicy(batch={self.batch!r}, seq={self.seq!r}, other={self.other!r})"


class SymbolicSpec:
    """Everything a symbolic cache entry needs at dispatch time.

    - ``marks``: tensor-leaf index -> {dim: (lo, hi, class_id)} — which dims
      are symbolic and their buckets (``hi`` is the padded extent);
    - ``classes``: class_id -> (leaf_idx, dim, lo, hi) — one class per marked
      dim; the representative (leaf, dim) is where the runtime true extent is
      read from;
    - ``mask_classes``: ordered class ids whose TRUE extents are appended as
      extra 0-d int32 inputs to the staged computation (set by the pad-mask
      transform when a masked reduction consumes them);
    - ``crop_plan``: [(flat output leaf index, {dim: class_id}), ...] from
      dim provenance (re-analyzed after grad/autocast transforms); an empty
      plan means no output carries padding and nothing is cropped.
    """

    __slots__ = ("marks", "classes", "mask_classes", "crop_plan")

    def __init__(self, marks: dict):
        self.marks = marks
        self.classes: dict[int, tuple] = {}
        for li, dims in sorted(marks.items()):
            for d, (lo, hi, cid) in sorted(dims.items()):
                self.classes[cid] = (li, d, lo, hi)
        self.mask_classes: tuple = ()
        self.crop_plan = None

    def padded_extent(self, cid: int) -> int:
        return self.classes[cid][3]

    def true_extents(self, flat_tensor_leaves) -> dict[int, int]:
        """class_id -> the CURRENT call's extent, read off the raw inputs."""
        out = {}
        for cid, (li, d, _lo, _hi) in self.classes.items():
            out[cid] = int(flat_tensor_leaves[li].shape[d])
        return out

    def describe(self) -> str:
        parts = []
        for li, dims in sorted(self.marks.items()):
            for d, (lo, hi, _cid) in sorted(dims.items()):
                parts.append(f"leaf{li}.dim{d}∈({lo},{hi}]")
        return " ".join(parts) or "exact"


def make_symbolic_spec(marks_dims: dict, shapes: dict, policy: BucketPolicy) -> SymbolicSpec:
    """Build a spec from ``{leaf_idx: iterable-of-dims}`` marks and the
    current call's ``{leaf_idx: shape}``; buckets come from ``policy``."""
    marks: dict[int, dict[int, tuple]] = {}
    cid = 0
    for li in sorted(marks_dims):
        if li not in shapes:
            raise ValueError(
                f"symbolic_dims: no tensor input leaf {li} (the call has "
                f"{len(shapes)} tensor leaves)"
            )
        shape = shapes[li]
        dmap: dict[int, tuple] = {}
        for d in sorted(set(marks_dims[li])):
            if d < 0 or d >= len(shape):
                raise ValueError(
                    f"symbolic_dims: dim {d} out of range for input leaf {li} of rank {len(shape)}"
                )
            lo, hi = policy.bucket(d, shape[d])
            dmap[d] = (lo, hi, cid)
            cid += 1
        if dmap:
            marks[li] = dmap
    return SymbolicSpec(marks)
