"""Pytree flatten/unflatten.

Reference parity: thunder/core/pytree.py, which wraps the external C++
``optree``. Here the native tree library is ``jax.tree_util`` — already the
idiomatic, C++-backed pytree on TPU. Proxies are leaves (jax treats unknown
types as leaves).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.tree_util as jtu

tree_flatten = jtu.tree_flatten
tree_unflatten = jtu.tree_unflatten
tree_map = jtu.tree_map
tree_leaves = jtu.tree_leaves
tree_structure = jtu.tree_structure


def tree_flatten_with_dataclass(x: Any):
    return jtu.tree_flatten(x)


def tree_map_only(typ, fn: Callable, tree: Any) -> Any:
    return jtu.tree_map(lambda v: fn(v) if isinstance(v, typ) else v, tree)
