"""Trace-time concretization of input-derived scalars, with value guards.

Reference parity: the reference's bytecode interpreter executes Python
branches on real tensor values natively (thunder/core/jit_ext.py — the VM
runs `if mask.all():` with a real torch tensor, and the resulting constraint
lands in the prologue via `unpack_inputs:1098`). This frontend's dispatch
interception has no VM, so the same capability is met with *guarded
concretization*: when traced Python coerces a TensorProxy to a Python scalar
(``bool()``/``int()``/``float()``), the proxy's producing subgraph is staged
and executed eagerly on the trace's concrete example inputs, the resulting
value is baked into the trace, and a VALUE GUARD — that same staged
subgraph plus an equality check — is attached to the cache entry. A later
call where the subgraph evaluates differently is a controlled cache miss
(retrace), never a silent reuse of a wrong specialization.

This is what lets unmodified HF models that branch on mask contents
(``transformers.masking_utils`` calls ``padding_mask.all()``) trace and
cache correctly.
"""

from __future__ import annotations

from typing import Any, Optional


class ValueGuard:
    """A staged scalar subprogram + the value it must reproduce."""

    __slots__ = ("fn", "kind", "expected", "description")

    def __init__(self, fn, kind: str, expected, description: str = ""):
        self.fn = fn
        self.kind = kind
        self.expected = expected
        self.description = description

    def evaluate(self, tensor_inputs) -> bool:
        import numpy as np

        raw = self.fn(*tensor_inputs)
        if raw is None:
            raise RuntimeError(f"value guard produced no value: {self.description}")
        got = np.asarray(raw).item()
        if self.kind == "bool":
            return bool(got) == self.expected
        return got == self.expected

    def __repr__(self) -> str:
        return f"<ValueGuard {self.kind} == {self.expected!r} ({self.description})>"


def concretize_scalar(proxy, kind: str) -> Optional[Any]:
    """Evaluate ``proxy`` on the active trace's concrete example inputs.

    Returns the Python scalar and records a ValueGuard on the trace, or
    returns None when the active trace has no concrete inputs (detached
    traces, meta-only tracing) — the caller then raises its usual
    data-dependent-control-flow error.
    """
    from thunder_tpu.core import prims
    from thunder_tpu.core.trace import TraceCtx, get_tracectx, tracectx

    trc = get_tracectx()
    if trc is None:
        return None
    leaves = getattr(trc, "_concrete_leaves", None)
    if leaves is None:
        return None

    from thunder_tpu.common import suppress_sharp_edges

    with suppress_sharp_edges():
        return _concretize_scalar(proxy, kind, trc, leaves)


def _concretize_scalar(proxy, kind: str, trc, leaves):
    from thunder_tpu.core import prims
    from thunder_tpu.core.trace import TraceCtx, tracectx
    from thunder_tpu.transforms.common import dce

    sub = TraceCtx()
    sub.name = "value_guard"
    sub.args = trc.args
    sub._names = set(trc._names)
    # extend in place — the trace's scope stack aliases this exact list
    sub.bound_symbols.extend(trc.bound_symbols)
    with tracectx(sub):
        prims.python_return(proxy)
    sub.output = proxy
    sub = dce(sub)

    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors

    # jax lowers the compute; python lowers python_return (without it the
    # staged callable silently returns None).
    ex = transform_for_execution(sub, resolve_executors(["jax", "python"]))
    fn = ex.python_callable()

    from thunder_tpu.executors import bridge

    vals = [bridge.to_jax(c) if bridge.is_concrete_tensor(c) else c for c in leaves]
    import numpy as np

    raw = fn(*vals)
    if raw is None:
        raise RuntimeError(f"concretization of {proxy.name} produced no value")
    value = {"bool": bool, "int": int, "float": float}[kind](np.asarray(raw).item())

    guards = getattr(trc, "_value_guards", None)
    if guards is None:
        guards = trc._value_guards = []
    guards.append(ValueGuard(fn, kind, value, f"{kind}({proxy.name})"))
    return value


def value_guards_of(trc) -> tuple:
    return tuple(getattr(trc, "_value_guards", ()) or ())


def check_value_guards(guards, tensor_inputs) -> bool:
    for g in guards:
        try:
            if not g.evaluate(tensor_inputs):
                return False
        except Exception:
            return False
    return True
