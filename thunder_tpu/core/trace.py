"""TraceCtx: the linear SSA-like program representation.

Reference parity: thunder/core/trace.py (`TraceCtx:46`, `python:309`,
`python_callable:400`, `from_trace:434`, tracectx contextvars `:453-474`,
`detached_trace:508`, `TraceProvenance:29`).

A trace is a list of ``BoundSymbol``s plus the signature (proxied args) and
output. It prints as valid Python and compiles to a callable. Every transform
is trace→trace and stamps a ``TraceProvenance`` so the full compilation
history is inspectable — reading the generated program is the primary
debugging tool, as in the reference.
"""

from __future__ import annotations

import contextvars
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional, Sequence

from thunder_tpu.core import baseutils, codeutils
from thunder_tpu.core.baseutils import check
from thunder_tpu.core.codeutils import SigInfo
from thunder_tpu.core.proxies import Proxy, TensorProxy
from thunder_tpu.core.pytree import tree_flatten
from thunder_tpu.core.symbol import BoundSymbol


class TraceProvenance:
    def __init__(self, pss: str):
        self.pss = pss

    def __repr__(self) -> str:
        return f"# Constructed by {self.pss}"


class TraceCtx:
    def __init__(self, fn: Optional[Callable] = None, *, prologue: bool = False):
        self.fn = fn
        self.args: tuple = ()
        self.kwargs: dict = {}
        self.output: Any = None
        self.bound_symbols: list[BoundSymbol] = []
        self._scopes: list[list[BoundSymbol]] = [self.bound_symbols]
        self._names: set[str] = set()
        self._counter = baseutils.NamedCounter()
        self.provenance: Optional[TraceProvenance] = None
        self.name: str = "prologue" if prologue else "computation"
        self._siginfo: Optional[SigInfo] = None
        # Free-form metadata transforms may attach (e.g. saved_for_backward).
        self.tags: dict[str, Any] = {}

    # -- naming --------------------------------------------------------------

    def make_name(self, prefix: str = "t") -> str:
        while True:
            name = f"{prefix}{self._counter.next(prefix)}"
            if name not in self._names:
                self._names.add(name)
                return name

    def add_name(self, name: str) -> None:
        # Strict: the trace IR is SSA, so a name registered twice means two
        # proxies would alias one name — the verifier's ssa rules depend on
        # registration being unique (reference: trace.py add_name raises too).
        check(
            name not in self._names,
            lambda: f"Name {name!r} is already registered in this trace",
            ValueError,
        )
        self._names.add(name)

    def has_name(self, name: str) -> bool:
        return name in self._names

    # -- scopes --------------------------------------------------------------

    def push_scope(self, scope: list) -> None:
        self._scopes.append(scope)

    def pop_scope(self) -> list:
        check(len(self._scopes) > 1, "Cannot pop the root scope")
        return self._scopes.pop()

    @property
    def current_scope(self) -> list:
        return self._scopes[-1]

    def add_bound_symbol(self, bsym: BoundSymbol) -> None:
        self.current_scope.append(bsym)

    # -- signature -----------------------------------------------------------

    @property
    def siginfo(self) -> SigInfo:
        if self._siginfo is not None:
            return self._siginfo
        params = []
        for a in self.args:
            if isinstance(a, Proxy):
                params.append(a.name)
            else:
                params.append(codeutils.prettyprint(a))
        return SigInfo(self.name, params)

    def set_siginfo(self, siginfo: SigInfo) -> None:
        self._siginfo = siginfo

    # -- codegen -------------------------------------------------------------

    def pass_name(self) -> Optional[str]:
        """The provenance pass name without its timing suffix (``"Transform
        for execution"`` from ``"Transform for execution (took 3.2 ms)"``) —
        the one parsing point shared by annotated codegen and
        instrumentation attribution (observability/instrument.py)."""
        if self.provenance is None:
            return None
        pss = self.provenance.pss
        cut = pss.find(" (took")
        return pss[:cut] if cut >= 0 else pss

    def _annotate_tag(self) -> str:
        """Compact pass-provenance tag for profiler scope names: the pass
        name with spaces collapsed — e.g. "Transform_for_execution"."""
        pss = self.pass_name()
        return (pss or self.name).replace(" ", "_")

    def python(self, *, print_depth: int = 1, include_header: bool = True, annotate: bool = False) -> str:
        """Render the trace as Python source. ``annotate=True`` wraps each
        value-producing op in ``jax.named_scope`` so op names flow into HLO
        metadata and profiler timelines (reference: thunder/core/profile.py:15
        `add_markers` via torch.profiler/NVTX, env THUNDER_ANNOTATE_TRACES).
        The scope name carries the trace-line index and the pass provenance
        (``L<idx>.<sym>#<pass>``), so a profiler row maps back to BOTH the
        generated line and the transform that produced it
        (docs/observability.md). The separator is ``#`` — not ``@`` — because
        JAX's name stack silently truncates scope names at ``@`` before they
        reach HLO metadata, which would drop the pass provenance from every
        profile (observability/attribution.py parses both spellings)."""
        lines: list[str] = []
        if include_header:
            if self.provenance is not None:
                lines.append(repr(self.provenance))
            lines.append("import thunder_tpu.core.dtypes as dtypes")
            lines.append("import thunder_tpu.core.devices as devices")
            lines.append("")
        lines.append(self.siginfo.prettyprint())
        body: list[str] = []
        tag = self._annotate_tag() if annotate else ""
        for i, bsym in enumerate(self.bound_symbols):
            if annotate and bsym.flat_proxy_outs:
                scope = f"L{i}.{bsym.sym.name}#{tag}"
                body.append(f"{baseutils.indent(1)}with __annotate_scope({scope!r}):")
                body.extend(bsym.python(indent=2, print_depth=print_depth))
            else:
                body.extend(bsym.python(indent=1, print_depth=print_depth))
        if not body:
            body = [f"{baseutils.indent(1)}pass"]
        lines.extend(body)
        return "\n".join(lines) + "\n"

    def gen_ctx(self) -> dict[str, Any]:
        """Build the exec namespace: every call target of every top-level
        bound symbol, plus dtypes/devices modules and per-bsym call ctx."""
        from thunder_tpu.core import dtypes, devices

        ctx: dict[str, Any] = {"dtypes": dtypes, "devices": devices}
        for bsym in self.bound_symbols:
            if bsym.sym.python_printer is not None:
                ctx.update(bsym._call_ctx)
                continue
            name, target = bsym.gen_call_target()
            if isinstance(target, tuple):  # (module label, module object)
                label, mod = target
                ctx[label] = mod
            else:
                existing = ctx.get(name)
                check(
                    existing is None or existing is target,
                    lambda: f"Name collision in generated code: {name}",
                )
                ctx[name] = target
            ctx.update(bsym._call_ctx)
        return ctx

    def python_callable(self, **exec_ctx) -> Callable:
        import os

        def _env_flag(name: str) -> bool:
            return os.environ.get(name, "").lower() not in ("", "0", "false", "off")

        # Either spelling enables annotation; an explicitly-disabled legacy
        # var ("0") must not shadow the new one.
        annotate = _env_flag("THUNDER_ANNOTATE_TRACES") or _env_flag("THUNDER_TPU_ANNOTATE_TRACES")
        source = self.python(include_header=False, annotate=annotate)
        ctx = self.gen_ctx()
        if annotate:
            import jax

            ctx["__annotate_scope"] = jax.named_scope
        ctx.update(exec_ctx)
        fn = baseutils.compile_and_exec(self.siginfo.name, source, ctx)
        fn.__thunder_trace__ = self
        return fn

    def __repr__(self) -> str:
        return self.python()


def from_trace(trc: TraceCtx) -> TraceCtx:
    """A new empty trace inheriting signature/names from ``trc``
    (reference: trace.py `from_trace:434`)."""
    new = TraceCtx(trc.fn)
    new.args = trc.args
    new.kwargs = trc.kwargs
    new.output = trc.output
    new.name = trc.name
    new._siginfo = trc._siginfo
    new._names = set(trc._names)
    new._counter = trc._counter  # share so fresh proxies never collide
    new.tags = dict(trc.tags)
    return new


# -- tracing context management ----------------------------------------------

_tracectx = contextvars.ContextVar("tracectx", default=None)

# Trace-level grad mode (torch.no_grad/enable_grad during acquisition):
# False ⇒ Symbol.__call__ detaches op outputs via stop_gradient, matching
# eager's "values computed under no_grad are leaves" semantics.
_grad_mode_ctx = contextvars.ContextVar("trace_grad_mode", default=True)


def get_tracectx() -> Optional[TraceCtx]:
    return _tracectx.get()


def set_tracectx(trace: TraceCtx):
    return _tracectx.set(trace)


def reset_tracectx(token) -> None:
    _tracectx.reset(token)


@contextmanager
def tracectx(trace: Optional[TraceCtx]):
    tok = _tracectx.set(trace)
    try:
        yield trace
    finally:
        _tracectx.reset(tok)


@contextmanager
def detached_trace():
    """A fresh throwaway trace context (reference: trace.py:508)."""
    trace = TraceCtx()
    with tracectx(trace):
        yield trace


# -- debug checks (the trace verifier's pipeline hook) ------------------------
#
# Every pass stamps provenance through wrap_in_trace_provenance/mark; with
# checks enabled, that stamping point ALSO runs the static verifier
# (thunder_tpu/analysis) on the pass output, so the first malformed trace is
# attributed to the pass that introduced it instead of surfacing as a cryptic
# codegen or runtime failure. Enabled per-compile via jit(debug_checks=True)
# (the contextvar) or process-wide via THUNDER_TPU_CHECKS=1.

_debug_checks_ctx = contextvars.ContextVar("trace_debug_checks", default=None)


def debug_checks_enabled() -> bool:
    v = _debug_checks_ctx.get()
    if v is not None:
        return v
    import os

    return os.environ.get("THUNDER_TPU_CHECKS", "").strip().lower() not in ("", "0", "false", "off")


@contextmanager
def debug_checks(enabled: Optional[bool]):
    """Scope the verifier on (True) or off (False); None defers to the
    enclosing scope / THUNDER_TPU_CHECKS environment variable."""
    if enabled is None:
        yield
        return
    tok = _debug_checks_ctx.set(bool(enabled))
    try:
        yield
    finally:
        _debug_checks_ctx.reset(tok)


def _maybe_verify(trc: TraceCtx) -> TraceCtx:
    if debug_checks_enabled():
        from thunder_tpu.analysis import verify_or_raise

        verify_or_raise(trc)
    return trc


def _record_pass(pass_name: str, elapsed_ms: Optional[float], trc: TraceCtx) -> None:
    """Observability tap on the provenance-stamping point every pass already
    flows through: per-pass duration → metrics histogram + a "pass" event in
    the JSONL log, correlated to the enclosing compile. Both sinks are
    no-ops (one flag/contextvar check) when observability is off."""
    from thunder_tpu.observability import events, metrics as obsm

    if obsm.enabled() and elapsed_ms is not None:
        obsm.PASS_MS.observe(elapsed_ms, **{"pass": pass_name})
    if events.active_log() is not None:
        events.emit_event(
            "pass",
            compile_id=events.current_compile_id(),
            name=pass_name,
            ms=elapsed_ms,
            n_bsyms=len(trc.bound_symbols),
            trace=trc.name,
        )


def wrap_in_trace_provenance(trc: TraceCtx, pass_name: str, start_ns: int) -> TraceCtx:
    elapsed_ms = (time.perf_counter_ns() - start_ns) / 1e6
    trc.provenance = TraceProvenance(f"{pass_name} (took {elapsed_ms:.2f} ms)")
    _record_pass(pass_name, elapsed_ms, trc)
    return _maybe_verify(trc)


def mark(trc: TraceCtx, pass_name: str) -> TraceCtx:
    trc.provenance = TraceProvenance(pass_name)
    _record_pass(pass_name, None, trc)
    return _maybe_verify(trc)
