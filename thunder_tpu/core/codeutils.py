"""Rendering trace values as Python source (reference: thunder/core/codeutils.py).

The product invariant inherited from the reference: every trace prints as
*valid, executable, readable Python*. These helpers render arguments —
proxies print as their names; dtypes/devices print as constructor calls that
resolve against the modules bound into the execution context.
"""

from __future__ import annotations

from numbers import Number
from typing import Any, Sequence

from thunder_tpu.core import dtypes, devices
from thunder_tpu.core.proxies import Proxy, NumberProxy, StringProxy, CollectionProxy, AnyProxy


class SigInfo:
    """Signature of a generated function: ordered parameter names plus
    optional varargs/varkwargs names."""

    def __init__(self, name: str, params: Sequence[str] = (), varargs: str | None = None, varkwargs: str | None = None):
        self.name = name
        self.params = list(params)
        self.varargs = varargs
        self.varkwargs = varkwargs

    def prettyprint(self) -> str:
        parts = list(self.params)
        if self.varargs:
            parts.append(f"*{self.varargs}")
        if self.varkwargs:
            parts.append(f"**{self.varkwargs}")
        return f"def {self.name}({', '.join(parts)}):"


def prettyprint(x: Any) -> str:
    """Render a trace value as a Python expression."""
    if isinstance(x, NumberProxy):
        # Static numbers print as literals; the prologue guards their values.
        return x.name
    if isinstance(x, (StringProxy, CollectionProxy, AnyProxy)):
        return x.name
    if isinstance(x, Proxy):
        return x.name
    if isinstance(x, str):
        return repr(x)
    if x is None or x is Ellipsis:
        return repr(x)
    if isinstance(x, float):
        # repr(float) round-trips (incl. inf/nan via float('...'))
        if x != x:
            return "float('nan')"
        if x == float("inf"):
            return "float('inf')"
        if x == float("-inf"):
            return "float('-inf')"
        return repr(x)
    if isinstance(x, (bool, int, complex)):
        return repr(x)
    if isinstance(x, Number):
        return repr(x)
    if isinstance(x, slice):
        return f"slice({prettyprint(x.start)}, {prettyprint(x.stop)}, {prettyprint(x.step)})"
    if isinstance(x, dtypes.dtype):
        return f"dtypes.{x.name}" + ("_" if x.weak else "")
    if isinstance(x, devices.Device):
        return f'devices.Device("{x}")'
    if isinstance(x, tuple):
        inner = ", ".join(prettyprint(v) for v in x)
        if len(x) == 1:
            inner += ","
        return f"({inner})"
    if isinstance(x, list):
        return f"[{', '.join(prettyprint(v) for v in x)}]"
    if isinstance(x, dict):
        return "{" + ", ".join(f"{prettyprint(k)}: {prettyprint(v)}" for k, v in x.items()) + "}"
    if isinstance(x, type):
        return x.__name__
    raise NotImplementedError(f"Cannot render {x!r} (type {type(x)}) as Python source")


def is_printable(x: Any) -> bool:
    try:
        prettyprint(x)
        return True
    except NotImplementedError:
        return False


def module_shortname(module_name: str) -> str:
    return module_name.rsplit(".", 1)[-1]


def to_printable_collection_str(out: Any) -> str:
    """Render a (possibly nested) output structure for a return statement."""
    return prettyprint(out)
