"""Dtype lattice for the trace IR.

Capability parity with the reference's dtype system (reference:
thunder/core/dtypes.py — `dtype:53`, `to_dtype:274`): a framework-owned set of
dtypes with weak/strong number variants used for Python-number type promotion,
plus mappings to/from the execution substrate's dtypes. Here the substrate is
JAX/XLA, so every dtype also maps to a ``jax.numpy`` dtype; torch mappings are
kept for the torch-facing frontend. Unlike the reference (CUDA-era lattice)
this one is TPU-first: bfloat16 is a first-class compute dtype and the fp8
types XLA supports (e4m3fn / e5m2) are included.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class dtype:
    """A framework dtype.

    ``weak`` marks dtypes arising from Python numbers; they lose to any
    strong (tensor) dtype of the same kind during promotion, mirroring
    torch's number-promotion semantics.
    """

    def __init__(self, name: str, *, kind: str, bytes: int, weak: bool = False):
        self._name = name
        self.kind = kind  # 'bool' | 'int' | 'uint' | 'float' | 'complex'
        self.bytes = bytes
        self.weak = weak

    @property
    def is_weak(self) -> bool:
        return self.weak

    @property
    def name(self) -> str:
        return self._name

    @property
    def shortname(self) -> str:
        return _SHORTNAMES.get(self._name, self._name)

    def __repr__(self) -> str:
        return f"dtypes.{self._name}" + ("_" if self.weak else "")

    def __str__(self) -> str:
        return self.__repr__()

    def __hash__(self) -> int:
        return hash((self._name, self.weak))

    def __eq__(self, other: Any) -> bool:
        if not isinstance(other, dtype):
            return NotImplemented
        return self._name == other._name and self.weak == other.weak


_SHORTNAMES = {
    "bool8": "b8",
    "uint8": "u8",
    "int8": "i8",
    "int16": "i16",
    "int32": "i32",
    "int64": "i64",
    "bfloat16": "bf16",
    "float16": "f16",
    "float32": "f32",
    "float64": "f64",
    "float8_e4m3": "f8_e4m3",
    "float8_e5m2": "f8_e5m2",
    "complex64": "c64",
    "complex128": "c128",
}

# Strong dtypes
bool8 = dtype("bool8", kind="bool", bytes=1)
uint8 = dtype("uint8", kind="uint", bytes=1)
uint16 = dtype("uint16", kind="uint", bytes=2)
uint32 = dtype("uint32", kind="uint", bytes=4)
uint64 = dtype("uint64", kind="uint", bytes=8)
int8 = dtype("int8", kind="int", bytes=1)
int16 = dtype("int16", kind="int", bytes=2)
int32 = dtype("int32", kind="int", bytes=4)
int64 = dtype("int64", kind="int", bytes=8)
float8_e4m3 = dtype("float8_e4m3", kind="float", bytes=1)
float8_e5m2 = dtype("float8_e5m2", kind="float", bytes=1)
bfloat16 = dtype("bfloat16", kind="float", bytes=2)
float16 = dtype("float16", kind="float", bytes=2)
float32 = dtype("float32", kind="float", bytes=4)
float64 = dtype("float64", kind="float", bytes=8)
complex64 = dtype("complex64", kind="complex", bytes=8)
complex128 = dtype("complex128", kind="complex", bytes=16)

# Weak variants (Python-number provenance)
bool8_ = dtype("bool8", kind="bool", bytes=1, weak=True)
int64_ = dtype("int64", kind="int", bytes=8, weak=True)
float32_ = dtype("float32", kind="float", bytes=4, weak=True)
float64_ = dtype("float64", kind="float", bytes=8, weak=True)
complex64_ = dtype("complex64", kind="complex", bytes=8, weak=True)

all_dtypes = (
    bool8,
    uint8,
    uint16,
    uint32,
    uint64,
    int8,
    int16,
    int32,
    int64,
    float8_e4m3,
    float8_e5m2,
    bfloat16,
    float16,
    float32,
    float64,
    complex64,
    complex128,
)

boolean_dtypes = (bool8, bool8_)
integer_dtypes = (uint8, int8, int16, int32, int64, bool8)
low_precision_dtypes = (bfloat16, float16, float8_e4m3, float8_e5m2)
float_dtypes = (float8_e4m3, float8_e5m2, bfloat16, float16, float32, float64)
complex_dtypes = (complex64, complex128)
inexact_dtypes = float_dtypes + complex_dtypes
exact_dtypes = (bool8, uint8, int8, int16, int32, int64)


def is_boolean_dtype(d: dtype) -> bool:
    return d.kind == "bool"


def is_integer_dtype(d: dtype) -> bool:
    return d.kind in ("int", "uint", "bool")


def is_nonboolean_integer_dtype(d: dtype) -> bool:
    return d.kind in ("int", "uint")


def is_float_dtype(d: dtype) -> bool:
    return d.kind == "float"


def is_complex_dtype(d: dtype) -> bool:
    return d.kind == "complex"


def is_inexact_dtype(d: dtype) -> bool:
    return d.kind in ("float", "complex")


def is_exact_dtype(d: dtype) -> bool:
    return d.kind in ("bool", "int", "uint")


def is_signed_integer_dtype(d: dtype) -> bool:
    return d.kind == "int"


def to_strong(d: dtype) -> dtype:
    if not d.weak:
        return d
    return _BY_NAME[d._name]


def weak_variant(d: dtype) -> dtype:
    return _WEAK_BY_NAME.get(d._name, d)


_BY_NAME = {d._name: d for d in all_dtypes}
_WEAK_BY_NAME = {d._name: d for d in (bool8_, int64_, float32_, float64_, complex64_)}


def corresponding_real_dtype(d: dtype) -> dtype:
    if d == complex64:
        return float32
    if d == complex128:
        return float64
    return d


def corresponding_complex_dtype(d: dtype) -> dtype:
    if d in (float64,):
        return complex128
    return complex64


# -- Python number types ------------------------------------------------------

_number_type_to_dtype = {
    bool: bool8_,
    int: int64_,
    float: float64_,
    complex: complex64_,
}

dtype_to_number_type = {
    "bool": bool,
    "int": int,
    "uint": int,
    "float": float,
    "complex": complex,
}


def numbertype_to_dtype(typ: type) -> dtype:
    return _number_type_to_dtype[typ]


def dtype_to_numbertype(d: dtype) -> type:
    return dtype_to_number_type[d.kind]


# -- JAX mapping --------------------------------------------------------------

_JNP_NAMES = {
    "bool8": "bool_",
    "float8_e4m3": "float8_e4m3fn",
    "float8_e5m2": "float8_e5m2",
}


def to_jax_dtype(d: dtype) -> Any:
    import jax.numpy as jnp

    return np.dtype(getattr(jnp, _JNP_NAMES.get(d._name, d._name)))


def finfo_max(d: dtype) -> float:
    """Largest finite value of a float dtype (torch.finfo(d).max parity).
    numpy's finfo rejects ml_dtypes (bfloat16, fp8) on this numpy version —
    ml_dtypes.finfo handles both those and the standard floats."""
    jd = to_jax_dtype(to_strong(d))
    try:
        return float(np.finfo(jd).max)
    except ValueError:
        import ml_dtypes

        return float(ml_dtypes.finfo(jd).max)


def from_jax_dtype(jd: Any) -> dtype:
    name = np.dtype(jd).name
    rev = {"bool": "bool8", "float8_e4m3fn": "float8_e4m3"}
    name = rev.get(name, name)
    d = _BY_NAME.get(name)
    if d is None:
        raise ValueError(f"Unsupported jax dtype {jd}")
    return d


# -- torch mapping (frontend only; torch is CPU-only in this build) ----------


def to_torch_dtype(d: dtype) -> Any:
    import torch

    names = {
        "bool8": "bool",
        "float8_e4m3": "float8_e4m3fn",
        "float8_e5m2": "float8_e5m2",
    }
    return getattr(torch, names.get(d._name, d._name))


def from_torch_dtype(td: Any) -> dtype:
    name = str(td).removeprefix("torch.")
    rev = {"bool": "bool8", "float8_e4m3fn": "float8_e4m3"}
    name = rev.get(name, name)
    d = _BY_NAME.get(name)
    if d is None:
        raise ValueError(f"Unsupported torch dtype {td}")
    return d


def to_dtype(x: Any, *, true_dtype: bool = False) -> dtype:
    """Canonicalize any dtype-like (framework dtype, jax/np dtype, torch
    dtype, Python number type, or a value) to a framework dtype.

    Reference parity: thunder/core/dtypes.py `to_dtype:274`.
    """
    if x is None:
        return None
    if isinstance(x, dtype):
        return x if true_dtype else to_strong(x)
    if isinstance(x, type) and issubclass(x, (bool, int, float, complex)):
        d = _number_type_to_dtype[x]
        return d if true_dtype else to_strong(d)
    if isinstance(x, (bool, int, float, complex)):
        d = _number_type_to_dtype[type(x)]
        return d if true_dtype else to_strong(d)
    # torch dtype?
    tname = str(type(x))
    if "torch" in tname or (hasattr(x, "is_floating_point") and not hasattr(x, "name")):
        try:
            return from_torch_dtype(x)
        except (ValueError, AttributeError):
            pass
    try:
        return from_jax_dtype(x)
    except (TypeError, ValueError):
        pass
    raise ValueError(f"Cannot convert {x!r} (type {type(x)}) to a dtype")
