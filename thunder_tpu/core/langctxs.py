"""Language contexts: pluggable method-resolution for proxies.

Reference parity: thunder/core/langctxs.py (`LanguageContext:17`,
`resolve_method:66`, `langctx` decorator). A language context decides what
``proxy.foo(...)`` and operator dunders mean while tracing — e.g. the torch
language resolves ``t.view`` to the torch-mirror symbol while the core
language exposes only the clang surface.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from typing import Any, Callable, Optional


class Languages:
    CLANG = "clang"
    TORCH = "torch"
    NUMPY = "numpy"


class LanguageContext:
    def __init__(self, name: str):
        self.name = name
        self._methods: dict[str, Callable] = {}

    def register_method(self, name: str, fn: Callable) -> None:
        self._methods[name] = fn

    def get_method(self, name: str) -> Callable:
        fn = self._methods.get(name)
        if fn is None:
            raise AttributeError(f"The {self.name} language has no method {name!r}")
        return fn

    def has_method(self, name: str) -> bool:
        return name in self._methods


_langctx_registry: dict[str, LanguageContext] = {}


def register_langctx(name: str, ctx: LanguageContext) -> LanguageContext:
    _langctx_registry[name] = ctx
    return ctx


def resolve_language(name: str) -> LanguageContext:
    return _langctx_registry[name]


_langctx_var = contextvars.ContextVar("langctx", default=None)


def get_langctx() -> LanguageContext:
    ctx = _langctx_var.get()
    if ctx is None:
        # The torch language is the default method-resolution table: the
        # framework's public surface mirrors torch (reference defaults to its
        # torch langctx the same way).
        try:
            return resolve_language(Languages.TORCH)
        except KeyError:
            return resolve_language(Languages.CLANG)
    return ctx


@contextmanager
def langctx_ctx(ctx: LanguageContext | str):
    if isinstance(ctx, str):
        ctx = resolve_language(ctx)
    tok = _langctx_var.set(ctx)
    try:
        yield ctx
    finally:
        _langctx_var.reset(tok)


def langctx(ctx: LanguageContext | str):
    """Decorator: run ``fn`` under the given language context."""

    def decorator(fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with langctx_ctx(ctx):
                return fn(*args, **kwargs)

        return wrapper

    return decorator


def resolve_method(name: str, *args, **kwargs) -> Optional[Callable]:
    """Find the current language's implementation of method ``name``.

    Reference parity: thunder/core/langctxs.py `resolve_method:66`.
    """
    ctx = get_langctx()
    if ctx.has_method(name):
        return ctx.get_method(name)
    # Fall back to clang for core ops absent from the active language.
    clang_ctx = _langctx_registry.get(Languages.CLANG)
    if clang_ctx is not None and clang_ctx.has_method(name):
        return clang_ctx.get_method(name)
    return None
