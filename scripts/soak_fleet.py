#!/usr/bin/env python
"""Fleet soak: sustained mixed-fault abuse with a committed goodput number.

The falsifiable half of ISSUE 11: run the FSDP×TP training workload (plus a
sidecar thunder-jit dispatch standing in for serving traffic) on the
virtual 8-device mesh for hundreds of steps under a **seeded random chaos
schedule** — host_loss, collective_hang, sdc, oom, preempt, ckpt_io,
interleaved and occasionally overlapping — with the fleet autopilot
(``resilience/autopilot.py``) deciding every recovery. The run must end
with ZERO unrecovered faults and ZERO unactuated decisions (the replay
correlation rules), and its headline is **goodput**:

    goodput = (useful_tokens / wall_s) × (1 − resilience_overhead_pct/100)

where ``useful_tokens`` counts each of the N steps once (re-executed steps
after a restore are waste, paid in ``wall_s``), ``wall_s`` is the whole
soak wall clock including every recovery/rebuild/restore, and the overhead
pct is the directly-measured steady-state cost of the watchdog + SDC guard
(the ``bench_multichip --resilience-overhead`` protocol). One number that
only improves if speed AND resilience hold simultaneously.

Output: one JSON line (the committed ``SOAK_r*.json`` series), gated by
``scripts/perf_report.py --history SOAK_r*.json --gate`` with soak-sized
noise floors. ``scripts/lint_traces.py --soak`` runs a short deterministic
smoke of this driver in CI.

Usage::

    python scripts/soak_fleet.py                          # 200 steps, seed 1
    python scripts/soak_fleet.py --steps 200 --faults 14 \
        --seed 1 --out SOAK_r01.json
    python scripts/soak_fleet.py --smoke                  # CI-sized run
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


# =============================================================================
# The seeded chaos schedule
# =============================================================================

# Every required seam appears at least once so each autopilot policy class
# is exercised on any seed: host_loss/collective_hang -> elastic_resume,
# sdc -> quarantine_rerun, oom -> deopt_escalate, preempt ->
# checkpoint_halt, ckpt_io -> the manager's own retry; the tiered-
# checkpoint seams (ISSUE 14) -> the snapshot pipeline degrades one tier
# and keeps going (torn/slow flush -> a later commit; corrupt replica ->
# the restore ladder's checksum fall-through); straggler (ISSUE 15) -> a
# sub-timeout slowdown the STREAMING DETECTORS must flag (anomaly event,
# positive detection lead) before any watchdog timeout would.
REQUIRED_SEAMS = ("host_loss", "collective_hang", "sdc", "oom", "ckpt_io",
                  "preempt", "snap_torn", "snap_corrupt", "snap_slow",
                  "straggler")

# Fault classes a streaming detector covers (ISSUE 15): the soak gate
# requires >=1 anomaly of the mapped kinds whenever the class was injected
# (perf_report checks soak_undetected_detector_classes == 0).
DETECTED_FAULT_CLASSES = {
    "straggler": ("step_time_drift", "goodput_drop", "host_spread"),
    "oom": ("recompile_storm",),
}
# The filler pool excludes preempt: each preempt is a full
# checkpoint-and-halt + process-restart cycle, and one per soak is the
# scenario; a schedule of mostly restarts would measure restart latency,
# not goodput under churn. It also excludes the snap seams: they are
# near-free by design, and padding the schedule with them would flatter
# the per-fault recovery number instead of stressing the heavy actuators.
FILLER_SEAMS = ("host_loss", "collective_hang", "sdc", "oom", "ckpt_io")
# Seams that fire lazily at a later seam visit (a background flush, a
# tiered restore) rather than at their trigger step.
_LAZY_SNAP_SEAMS = ("snap_torn", "snap_slow")


@dataclass
class ScheduledFault:
    """One schedule entry: ``seam`` is armed at the end of ``step`` (so it
    fires on step+1's boundary/dispatch). Entries sharing a ``step`` are an
    overlapping pair — both armed before either recovery runs. ``target``
    carries a seam-specific target clause (the snap_corrupt tier)."""

    step: int
    seam: str
    target: str = None


def make_schedule(seed: int, n_steps: int, n_faults: int,
                  overlap_pairs: int = 2) -> list[ScheduledFault]:
    """Deterministic mixed-fault schedule: ``n_faults`` events over
    ``n_steps`` steps, covering every REQUIRED_SEAMS kind, with
    ``overlap_pairs`` of them sharing a trigger step (arriving before the
    prior fault's recovery has run). Same seed → same schedule.

    Tiered-checkpoint seams get special placement: ``snap_torn``/
    ``snap_slow`` fire at the NEXT background flush, so they are pinned
    into the early third of the run (armed at the tail they would never
    see a flush and never inject); ``snap_corrupt`` fires at the next
    tiered restore, so it is co-scheduled onto an elastic-driving fault's
    step (host_loss/collective_hang — whose recovery IS a restore) and
    targets the local tier, forcing the ladder through the buddy
    replica."""
    if n_faults < len(REQUIRED_SEAMS):
        raise ValueError(
            f"need at least {len(REQUIRED_SEAMS)} faults to cover every seam"
        )
    rng = random.Random(seed)
    seams = list(REQUIRED_SEAMS)
    while len(seams) < n_faults:
        pick = rng.choice(FILLER_SEAMS)
        # The de-opt ladder is 3 levels deep and sticky per function: a 4th
        # oom would exhaust it and (correctly) kill the run — cap the
        # schedule at what the ladder can absorb.
        if pick == "oom" and seams.count("oom") >= 3:
            continue
        seams.append(pick)
    # The recompile-storm detector needs >=2 recompiles inside its window
    # (ISSUE 15): with any filler slots at all, guarantee a second oom so
    # the storm anomaly is deterministic on every seed.
    if len(seams) > len(REQUIRED_SEAMS) and seams.count("oom") < 2:
        seams[len(REQUIRED_SEAMS)] = "oom"
    rng.shuffle(seams)
    # The preempt goes late: everything after it replays in the "restarted
    # process", and a very early halt would leave most faults untested
    # before the restart. It must land in the SLOT region (the first
    # n_slots seams get their own trigger step) — in the overlap tail it
    # would be co-scheduled onto another fault's step, whose recovery
    # would then fire in no process after the halt.
    n_slots = n_faults - overlap_pairs
    seams.remove("preempt")
    seams.insert(min(int(len(seams) * 0.6), max(0, n_slots - 1)), "preempt")
    lo, hi = 3, max(4, n_steps - 4)
    spacing = max(3, (hi - lo) // max(1, n_slots))
    slots = []
    for i in range(n_slots):
        base = lo + i * spacing
        slots.append(min(hi, base + rng.randrange(max(1, spacing - 2))))
    schedule = [ScheduledFault(step, seam) for step, seam in zip(slots, seams)]
    # Overlapping pairs: the remaining seams land ON an existing slot.
    # A preempt never overlaps (its recovery is a process exit — the pair's
    # second fault would fire in nobody's process).
    candidates = [f for f in schedule if f.seam != "preempt"]
    for seam in seams[n_slots:]:
        host = rng.choice(candidates)
        schedule.append(ScheduledFault(host.step, seam))
    # Tiered-checkpoint seam placement (docstring): torn/slow flush seams
    # must still have a flush ahead of them; a corrupted replica must have
    # a restore ahead of it.
    preempt_steps = {f.step for f in schedule if f.seam == "preempt"}
    early_hi = lo + max(3, (hi - lo) // 3)
    for f in schedule:
        if f.seam in _LAZY_SNAP_SEAMS and f.step > early_hi:
            step = lo + rng.randrange(max(1, early_hi - lo))
            while step in preempt_steps:
                step = lo + rng.randrange(max(1, early_hi - lo))
            f.step = step
    # Straggler placement (ISSUE 15): late enough that the step-time
    # detectors have a baseline (min_samples of clean steps), and with at
    # least one elastic-driving fault still AHEAD of it — the anomaly must
    # precede a hang/host-loss decision for detection lead to be positive
    # and measurable.
    straggler_step = None
    for f in schedule:
        if f.seam == "straggler":
            f.step = min(10 + rng.randrange(4), hi)
            while f.step in preempt_steps:
                f.step += 1
            straggler_step = f.step
    elastic_hosts = [f for f in schedule
                     if f.seam in ("host_loss", "collective_hang")]
    if straggler_step is not None and elastic_hosts and not any(
            f.step > straggler_step + 2 for f in elastic_hosts):
        # Every hang/host-loss landed before the straggler window: push the
        # latest one past it so its decision can cite the anomaly.
        latest = max(elastic_hosts, key=lambda f: f.step)
        latest.step = min(straggler_step + 4 + rng.randrange(3), hi)
        while latest.step in preempt_steps:
            latest.step += 1
    # snap_corrupt co-schedules AFTER the adjustments above so the restore
    # that must follow it really does (the host it rides may have moved).
    for f in schedule:
        if f.seam == "snap_corrupt" and elastic_hosts:
            f.step = rng.choice(elastic_hosts).step
            f.target = "local"
    # Re-pinning (lazy snap seams, the straggler, the elastic adjustment)
    # can strand an overlap-tail entry alone on its step: repair by
    # co-scheduling movable mid-weight seams (armed-at-step, position-
    # insensitive) until the requested pairs are back.
    def _pairs() -> int:
        by_step: dict[int, int] = {}
        for f in schedule:
            by_step[f.step] = by_step.get(f.step, 0) + 1
        return sum(n - 1 for n in by_step.values() if n > 1)

    while _pairs() < overlap_pairs:
        counts: dict[int, int] = {}
        for f in schedule:
            counts[f.step] = counts.get(f.step, 0) + 1
        movable = [f for f in schedule
                   if f.seam in ("sdc", "ckpt_io", "oom")
                   and counts[f.step] == 1]
        targets = [f for f in schedule
                   if f.seam not in ("preempt", "straggler")
                   and f.step not in preempt_steps]
        if not movable:
            break
        mover = movable[-1]
        choices = [f for f in targets
                   if f is not mover and f.step != mover.step]
        if not choices:
            break
        mover.step = rng.choice(choices).step
    schedule.sort(key=lambda f: (f.step, f.seam))
    return schedule


def overlapping_pairs(schedule: list[ScheduledFault]) -> int:
    by_step: dict[int, int] = {}
    for f in schedule:
        by_step[f.step] = by_step.get(f.step, 0) + 1
    return sum(n - 1 for n in by_step.values() if n > 1)


def arm_fault(cfg, fault: ScheduledFault, *, hang_delay_s: float) -> None:
    """Append ``fault``'s FaultRule to the LIVE chaos config — the soak's
    step callback arms each scheduled fault at its trigger step, which is
    what lets two entries overlap deterministically (both rules armed
    before either recovery runs)."""
    from thunder_tpu.resilience.chaos import FaultRule

    seam = fault.seam
    if seam in ("host_loss", "preempt"):
        # Step-targeted: fires at the NEXT step's boundary check.
        cfg.rules.append(FaultRule(seam, target=str(fault.step + 1)))
    elif seam == "collective_hang":
        cfg.rules.append(FaultRule(seam, delay_s=hang_delay_s))
    elif seam == "snap_slow":
        # A slow flush must be slow relative to the flush cadence so the
        # single-in-flight backpressure actually coalesces behind it, but
        # must not dwarf the recovery budget it rides in.
        cfg.rules.append(FaultRule(seam, delay_s=min(1.0, hang_delay_s / 4)))
    elif seam == "snap_corrupt":
        # Fires at the next tiered restore; the target picks the tier(s).
        cfg.rules.append(FaultRule(seam, target=fault.target or "local"))
    elif seam == "straggler":
        # Sub-timeout slowdown over several consecutive guarded steps
        # (target "step" fires inside watchdog.guard_call, never on the
        # sidecar): big vs the ms-scale CPU-mesh step, far below the
        # watchdog timeout — only the streaming detectors can see it.
        cfg.rules.append(FaultRule(seam, target="step", count=5,
                                   delay_s=hang_delay_s / 200.0))
    else:  # sdc, oom, ckpt_io, snap_torn: fire at their next seam visit
        cfg.rules.append(FaultRule(seam))


# =============================================================================
# The soak run
# =============================================================================


def _build_workload(args):
    """The FSDP×TP training workload + per-mesh builders (the
    lint_traces --chaos-multihost idiom) and the sidecar thunder-jit
    dispatch (the 'serving traffic' that owns the oom/de-opt seam)."""
    import numpy as np

    import thunder_tpu as ttpu
    import thunder_tpu.torch as ttorch
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.parallel import build_train_step, make_mesh
    from thunder_tpu.parallel.sharding import gpt_param_specs
    from thunder_tpu.parallel.train import opt_state_specs

    cfg = m.name_to_config(args.model)
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    rng = np.random.RandomState(args.seed)
    idx = rng.randint(0, cfg.vocab_size, (args.batch, args.seq)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    from thunder_tpu.resilience.elastic import mesh_shape

    step_cache: dict = {}

    def build_for_mesh(mesh):
        key = tuple(sorted((mesh_shape(mesh) or {}).items()))
        if key in step_cache:
            return step_cache[key]
        specs = gpt_param_specs(cfg, mesh)
        step, _ = build_train_step(
            cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=1e-2,
            executors=["jax"], donate=False,
        )

        def step_fn(state):
            p, o = state
            p, o, loss = step(p, o, idx, tgt)
            return (p, o), float(np.asarray(loss))

        step_cache[key] = step_fn
        return step_fn

    def specs_for_mesh(mesh):
        p_specs = gpt_param_specs(cfg, mesh)
        return (p_specs, opt_state_specs(p_specs))

    mesh = make_mesh(fsdp=args.devices // 2, tp=2)
    # Build the opt state once on the full mesh.
    specs = gpt_param_specs(cfg, mesh)
    _, opt0 = build_train_step(
        cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=1e-2,
        executors=["jax"], donate=False,
    )

    # Sidecar "serving" dispatch: a thunder-jit function whose dispatches
    # run through api._run_entry — the seam where oom fires and the de-opt
    # ladder (deopt_escalate decisions) recovers.
    xa = rng.randn(4, 8).astype(np.float32)
    wa = rng.randn(6, 8).astype(np.float32)
    sidecar = ttpu.jit(
        lambda a, w: ttorch.sum(ttorch.gelu(ttorch.linear(a, w))),
        executors=["jax"],
    )

    tokens_per_step = args.batch * args.seq
    return (mesh, (params, opt0), build_for_mesh, specs_for_mesh,
            lambda: sidecar(xa, wa), tokens_per_step)


def _measure_overheads(step_fn, state, mesh, n: int = 6):
    """(ideal tokens-per-step denominator, resilience_overhead_pct): the
    bench_multichip --resilience-overhead protocol — median clean step,
    median SDC checksum, median watchdog spawn, overhead measured directly
    (loop-vs-loop deltas drown in CPU-mesh jitter)."""
    from thunder_tpu.resilience.watchdog import SDCGuard, guard_call

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    guard = SDCGuard(check_every=1)
    steps, checks = [], []
    for _ in range(max(4, n)):
        t0 = time.perf_counter()
        state, _ = step_fn(state)
        t1 = time.perf_counter()
        steps.append(t1 - t0)
        guard.check_state(state)
        checks.append(time.perf_counter() - t1)
    spawns = []
    noop = lambda: None  # noqa: E731
    for _ in range(20):
        t0 = time.perf_counter()
        guard_call(noop, (), fn_name="noop", timeout_s=60.0)
        spawns.append(time.perf_counter() - t0)
    step_s, check_s, spawn_s = med(steps), med(checks), med(spawns)
    overhead_pct = ((check_s + spawn_s) / step_s * 100.0) if step_s else 0.0
    return step_s, overhead_pct, state


def run_soak(args) -> dict:
    import thunder_tpu.monitor as monitor
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events
    from thunder_tpu.observability import metrics as obsm
    from thunder_tpu.resilience import autopilot as ap_mod
    from thunder_tpu.resilience import chaos
    from thunder_tpu.resilience.chaos import ChaosConfig
    from thunder_tpu.resilience.preemption import CheckpointManager

    import tempfile

    tmp = args.workdir or tempfile.mkdtemp(prefix="ttpu_soak_")
    log = os.path.join(tmp, "events.jsonl")
    monitor.set_event_log(log)

    # The schedule is built FIRST (deterministic per seed) so the detector
    # config below can be sized to what it will actually inject.
    schedule = make_schedule(args.seed, args.steps, args.faults,
                             overlap_pairs=args.overlap_pairs)
    n_ooms = sum(1 for f in schedule if f.seam == "oom")

    # Live ops plane (ISSUE 15): the soak runs scrapeable — per-host
    # /metrics + /healthz on an ephemeral port, the flight recorder dumping
    # on every timeout/SDC/halt, and the streaming detectors (tuned to the
    # soak's compressed timescale) feeding anomalies into the autopilot.
    plane = None
    flightrec_dir = os.path.join(tmp, "flightrec")
    if args.ops_plane:
        from thunder_tpu.observability import opsplane
        from thunder_tpu.observability.detect import DetectorConfig

        plane = opsplane.enable(
            port=0, serve=True,
            flightrec_dir=flightrec_dir, flightrec_keep=64,
            detectors=DetectorConfig(
                min_samples=6, cooldown=20, goodput_consecutive=3,
                # N recompiles inside the run = a storm at soak scale,
                # sized to the schedule's oom count (>=2 whenever it has a
                # filler slot; a minimum-size schedule carries one oom and
                # the gate must stay deterministic, not hope for
                # incidental recompiles).
                recompile_threshold=min(2, max(1, n_ooms)),
                recompile_window_s=3600.0,
            ),
        )
        _log(f"ops plane: http://127.0.0.1:{plane.port} "
             f"(/metrics /healthz /debug/state); flight recorder -> "
             f"{flightrec_dir}")

    (mesh, state0, build_for_mesh, specs_for_mesh, sidecar,
     tokens_per_step) = _build_workload(args)
    from thunder_tpu.resilience.elastic import mesh_shape

    _log(f"workload: {args.model} B={args.batch} T={args.seq} "
         f"mesh={mesh_shape(mesh)}")

    # Warm the full-mesh step + sidecar, then measure the ideal step and
    # the resilience overhead OUTSIDE the soak wall clock.
    step_fn = build_for_mesh(mesh)
    state, _ = step_fn(state0)
    sidecar()
    ideal_step_s, overhead_pct, _ = _measure_overheads(step_fn, state, mesh)
    ideal_tps = tokens_per_step / ideal_step_s if ideal_step_s else 0.0
    _log(f"ideal step {ideal_step_s * 1e3:.1f}ms -> {ideal_tps:.0f} tok/s; "
         f"resilience overhead {overhead_pct:.2f}%")

    n_overlap = overlapping_pairs(schedule)
    by_seam: dict[str, int] = {}
    for f in schedule:
        by_seam[f.seam] = by_seam.get(f.seam, 0) + 1
    _log(f"schedule (seed={args.seed}): "
         + ", ".join(f"{f.seam}@{f.step}" for f in schedule)
         + f" ({n_overlap} overlapping pair(s))")

    by_step: dict[int, list] = {}
    for f in schedule:
        by_step.setdefault(f.step, []).append(f)

    cfg = ChaosConfig(rules=[], seed=args.seed)
    # Hysteresis windows sized to the soak's compressed timescale: the
    # production defaults (minutes) span the entire CPU-mesh run, which
    # would make every repeated fault look like flapping.
    policies = ap_mod.default_policies()
    for pol in policies.values():
        pol.window_s = min(pol.window_s, args.hysteresis_window_s)
    autopilot = ap_mod.Autopilot(policies=policies)

    def fresh_manager():
        # Tiered checkpointing (ISSUE 14): a local RAM ring buddy-paired
        # with a peer store (the virtual-mesh stand-in for replicating
        # shards to another host) + the async background disk writer. A
        # restart gets a FRESH pair — the next allocation's RAM starts
        # empty, disk is the only tier that survives a process death.
        from thunder_tpu.resilience.snapshot import SnapshotStore

        store = SnapshotStore(host=0, ring=args.snapshot_ring)
        buddy = SnapshotStore(host=1, ring=args.snapshot_ring)
        SnapshotStore.pair(store, buddy)
        return CheckpointManager(os.path.join(tmp, "ckpt"), keep=3,
                                 backoff_s=0.01, store=store,
                                 async_flush=True)

    mgr = fresh_manager()

    armed: set = set()

    def on_step(step, loss):
        # Sidecar dispatch first (an armed oom fires here), then arm
        # whatever the schedule planted at this step. Each entry arms at
        # most once — steps re-executed after a restore must not re-plant
        # faults that already fired (that would turn one scheduled hang
        # into an unbounded thrash loop).
        sidecar()
        for fault in by_step.get(step, ()):  # same step = overlapping
            if id(fault) in armed:
                continue
            armed.add(id(fault))
            arm_fault(cfg, fault, hang_delay_s=args.watchdog_timeout_s * 6)

    halts = 0
    losses: list = [None] * args.steps
    reports = []
    wall0 = time.perf_counter()
    with chaos.chaos_scope(cfg):
        while True:
            try:
                state, report = ap_mod.run_autopiloted_training(
                    autopilot, build_for_mesh, state0, args.steps,
                    manager=mgr, mesh=mesh, specs_for_mesh=specs_for_mesh,
                    sdc_guard=True,
                    watchdog_timeout_s=args.watchdog_timeout_s,
                    save_every=args.save_every,
                    snapshot_every=args.snapshot_every, on_step=on_step,
                    regrow_after=args.regrow_after,
                )
                reports.append(report)
                break
            except ap_mod.AutopilotHalt as e:
                # A checkpoint_halt landed (preemption or exhausted ladder):
                # the durable checkpoint exists; "the next allocation"
                # resumes — same process, fresh driver call with EMPTY RAM
                # tiers (only disk survives a process death; the restart's
                # first restore is the soak's disk-tier coverage).
                if e.report is not None:
                    reports.append(e.report)
                halts += 1
                mgr.close()
                mgr = fresh_manager()
                _log(f"halt #{halts}: {e} — restarting from the checkpoint")
                if halts > args.max_restarts:
                    raise RuntimeError(
                        f"soak exceeded {args.max_restarts} restarts"
                    ) from e
    mgr.close()  # drain the background writer: every flush event must land
    wall_s = time.perf_counter() - wall0
    for report in reports:
        for i, v in enumerate(report.losses):
            if v is not None:
                losses[i] = v
    steps_executed = sum(r.steps_executed for r in reports)

    ops_healthz = None
    ops_port = plane.port if plane is not None else None
    if plane is not None:
        # One end-of-run scrape proves the endpoints served a real run.
        try:
            import urllib.error
            import urllib.request

            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{plane.port}/healthz", timeout=5) as r:
                    body = r.read().decode()
            except urllib.error.HTTPError as e:
                body = e.read().decode()  # 503 = a served "critical" verdict
            ops_healthz = json.loads(body).get("status")
        except Exception as e:
            ops_healthz = f"unreachable: {e}"

    monitor.set_event_log(None)
    summary, diags = replay_events(log, storm_threshold=64)
    errors = [d for d in diags if d.severity >= Severity.ERROR]
    for line in format_replay(summary, diags).splitlines():
        _log(line)

    # Ops-plane accounting (ISSUE 15), all from durable artifacts: anomaly
    # counts from the replayed log; detection lead from decisions whose
    # evidence cites a detector anomaly (decision ts − anomaly ts > 0 means
    # the detectors saw the fault coming); flight-recorder dumps validated
    # file by file against the same schema + correlation rules.
    anomalies = dict(summary.get("anomalies") or {})
    leads: list = []
    cited = 0
    with open(log) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") != "autopilot_decision":
                continue
            ev = rec.get("evidence")
            an = ev.get("anomaly") if isinstance(ev, dict) else None
            if not an:
                continue
            cited += 1
            try:
                leads.append(float(rec["ts"]) - float(an["ts"]))
            except (KeyError, TypeError, ValueError):
                pass
    positive_leads = [l for l in leads if l > 0]
    detection_lead = round(max(positive_leads), 3) if positive_leads else 0.0
    undetected = sorted(
        seam for seam, kinds in DETECTED_FAULT_CLASSES.items()
        if by_seam.get(seam) and not any(anomalies.get(k) for k in kinds)
    )
    import glob as _glob

    dump_paths = sorted(_glob.glob(
        os.path.join(flightrec_dir, "flightrec-*.jsonl")))
    n_invalid = 0
    dump_reasons: dict = {}
    for p in dump_paths:
        dsum, ddiags = replay_events(p)
        if any(d.severity >= Severity.ERROR for d in ddiags):
            n_invalid += 1
        with open(p) as f:
            last = f.readlines()[-1]
        try:
            reason = str(json.loads(last).get("reason"))
        except ValueError:
            reason = "?"
        dump_reasons[reason] = dump_reasons.get(reason, 0) + 1
    timeouts = int(summary.get("kinds", {}).get("collective_timeout") or 0)
    dumps_missing = (
        max(0, timeouts - dump_reasons.get("collective_timeout", 0))
        + max(0, halts - dump_reasons.get("autopilot_halt", 0))
    ) if plane is not None else 0
    if plane is not None:
        from thunder_tpu.observability import opsplane

        opsplane.disable()

    useful_tokens = args.steps * tokens_per_step
    tps = useful_tokens / wall_s if wall_s else 0.0
    goodput = tps * (1.0 - overhead_pct / 100.0)
    ratio = goodput / ideal_tps if ideal_tps else 0.0
    # Wall time not spent on ideal-speed useful steps, charged per fault:
    # the machine-portable cost-of-a-fault number (the goodput RATIO swings
    # with the machine's ideal step time, which the CPU mesh cannot hold
    # steady run to run).
    n_faults = len(summary.get("faults_injected") or []) or 1
    recovery_per_fault_s = max(0.0, wall_s - args.steps * ideal_step_s) / n_faults
    if obsm.enabled():
        obsm.SOAK_GOODPUT.set(goodput)
    # The goodput record goes to the log AFTER replay on purpose: the
    # summary it carries (unrecovered/unactuated) is the replay's verdict.
    monitor.set_event_log(log)
    from thunder_tpu.observability.events import emit_event

    emit_event(
        "goodput", goodput_tokens_per_sec=round(goodput, 1),
        tokens_per_sec=round(tps, 1), useful_tokens=useful_tokens,
        wall_s=round(wall_s, 2), overhead_pct=round(overhead_pct, 2),
        steps=args.steps,
    )
    monitor.set_event_log(None)

    result = {
        "metric": "soak_goodput",
        "value": round(goodput, 1),
        "unit": "tokens/s",
        "seed": args.seed,
        "n_devices": args.devices,
        "mesh": mesh_shape(mesh),
        "model": args.model,
        "batch": args.batch,
        "seq": args.seq,
        "steps": args.steps,
        "soak_goodput_tokens_per_sec": round(goodput, 1),
        "soak_tokens_per_sec": round(tps, 1),
        "soak_ideal_tokens_per_sec": round(ideal_tps, 1),
        "soak_goodput_ratio": round(ratio, 4),
        "resilience_overhead_pct": round(overhead_pct, 2),
        "soak_wall_s": round(wall_s, 2),
        "soak_recovery_per_fault_s": round(recovery_per_fault_s, 2),
        "soak_faults_injected": len(summary.get("faults_injected") or []),
        "soak_fault_seams": by_seam,
        "soak_overlapping_pairs": n_overlap,
        "soak_decisions": summary.get("autopilot_decisions") or {},
        "soak_unrecovered": len(summary.get("unrecovered_faults") or []),
        "soak_unactuated": len(summary.get("unactuated_decisions") or []),
        "soak_replay_errors": len(errors),
        "soak_restarts": halts,
        "soak_steps_executed": steps_executed,
        "soak_final_loss": losses[-1],
        # Tiered checkpointing (ISSUE 14), all derived from the replayed
        # event log: the amortized hot-path stall of the snapshot cadence,
        # where restores landed on the tier ladder, and how many fell
        # through an invalid tier (the chaos seams' visible recovery).
        "checkpoint_stall_ms_per_step": round(
            float(summary.get("snapshot_stall_ms_total") or 0.0) / args.steps, 3),
        "snapshot_every": args.snapshot_every,
        "soak_snapshots": summary.get("snapshots") or 0,
        "soak_restore_tiers": summary.get("restore_tiers") or {},
        "soak_restore_fallthroughs": summary.get("restore_fallthroughs") or 0,
        # Live ops plane (ISSUE 15): streaming-detector anomalies, the
        # detection lead (max positive decision-ts − cited-anomaly-ts: >0
        # means a detector flagged the fault before the autopilot had to
        # act on it), detector coverage per fault class, and the flight
        # recorder's per-fault black-box dumps (validated against the event
        # schema + correlation rules, one by one).
        "soak_ops_port": ops_port,
        "soak_ops_healthz": ops_healthz,
        "soak_anomalies": anomalies,
        "soak_anomalies_total": sum(anomalies.values()),
        "soak_detection_lead": detection_lead,
        "soak_decisions_citing_anomaly": cited,
        "soak_undetected_detector_classes": len(undetected),
        "soak_detector_classes_missed": undetected,
        "soak_flightrec_dumps": len(dump_paths),
        "soak_flightrec_by_reason": dump_reasons,
        "soak_flightrec_invalid": n_invalid,
        "soak_flightrec_missing": dumps_missing,
        "events_log": log,
    }
    _log(f"goodput {goodput:.0f} tok/s ({ratio * 100:.1f}% of ideal "
         f"{ideal_tps:.0f}) over {wall_s:.1f}s wall; "
         f"{result['soak_faults_injected']} faults, "
         f"{sum(result['soak_decisions'].values())} decisions, "
         f"{halts} restart(s), unrecovered={result['soak_unrecovered']}, "
         f"unactuated={result['soak_unactuated']}")
    _log(f"tiers: {result['soak_snapshots']} snapshots "
         f"(stall {result['checkpoint_stall_ms_per_step']:.2f} ms/step), "
         f"restores "
         + (", ".join(f"{t}×{n}" for t, n in
                      sorted(result['soak_restore_tiers'].items())) or "none")
         + f", {result['soak_restore_fallthroughs']} fall-through(s)")
    if plane is not None:
        _log(f"ops: anomalies "
             + (", ".join(f"{k}×{n}" for k, n in sorted(anomalies.items()))
                or "none")
             + f"; detection lead {detection_lead:.2f}s over {cited} cited "
             f"decision(s); dumps "
             + (", ".join(f"{r}×{n}" for r, n in sorted(dump_reasons.items()))
                or "none")
             + f" ({n_invalid} invalid, {dumps_missing} missing); "
             f"healthz={ops_healthz}")
    return result


# =============================================================================
# Driver
# =============================================================================


def soak_ok(result: dict) -> bool:
    """The soak's pass condition (the acceptance gate): nothing unrecovered,
    nothing unactuated, no replay errors, a finite final loss — and, with
    the ops plane on (ISSUE 15), every detector-covered fault class raised
    an anomaly, detection lead is positive, and every timeout/halt produced
    a schema-valid flight-recorder dump."""
    loss = result.get("soak_final_loss")
    ok = (
        result.get("soak_unrecovered") == 0
        and result.get("soak_unactuated") == 0
        and result.get("soak_replay_errors") == 0
        and loss is not None and loss == loss  # not NaN
    )
    if ok and result.get("soak_ops_port") is not None:
        ok = (
            result.get("soak_undetected_detector_classes") == 0
            and result.get("soak_detection_lead", 0) > 0
            and result.get("soak_flightrec_invalid") == 0
            and result.get("soak_flightrec_missing") == 0
        )
    return ok


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="soak_fleet.py",
        description="Goodput-gated chaos soak on the virtual mesh (SOAK series)",
    )
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--model", default="gpt-tiny")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--faults", type=int, default=14)
    p.add_argument("--overlap-pairs", type=int, default=2)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--save-every", type=int, default=10)
    p.add_argument("--snapshot-every", type=int, default=3,
                   help="RAM-snapshot cadence in steps (ISSUE 14: a fault "
                        "loses at most this many steps instead of "
                        "save-every)")
    p.add_argument("--snapshot-ring", type=int, default=4,
                   help="snapshots kept per RAM tier (local ring and buddy "
                        "replica ring)")
    p.add_argument("--watchdog-timeout-s", type=float, default=2.0)
    p.add_argument("--hysteresis-window-s", type=float, default=15.0,
                   help="cap on every policy's hysteresis window (the "
                        "production defaults span the whole CPU-mesh run)")
    p.add_argument("--regrow-after", type=int, default=15,
                   help="healthy steps on a shrunk mesh before resharding "
                        "back up to the full mesh (0 disables)")
    p.add_argument("--max-restarts", type=int, default=8)
    p.add_argument("--ops-plane", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="live ops plane (ISSUE 15): /metrics + /healthz on "
                        "an ephemeral port, flight-recorder dumps per "
                        "fault, streaming detectors feeding the autopilot")
    p.add_argument("--smoke", action="store_true",
                   help="CI-sized run: 40 steps, 11 faults (lint_traces --soak)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--out", default=None, help="also write the JSON here")
    p.add_argument("--_subprocess", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.smoke:
        # 11 faults = every required seam + one filler slot, which the
        # schedule turns into the second oom the recompile-storm detector
        # needs (ISSUE 15).
        args.steps, args.faults, args.save_every = 40, 11, 5
        args.snapshot_every = 2
        args.regrow_after = 10
    if not args.regrow_after:
        args.regrow_after = None

    import jax

    if len(jax.devices()) < args.devices and not args._subprocess:
        # Backend already initialized with fewer devices: re-exec on the
        # virtual CPU mesh (the bench_multichip pattern).
        import subprocess

        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={args.devices}",
            "THUNDER_TPU_RETRY_BACKOFF_S": "0",
        }
        cmd = [sys.executable, os.path.abspath(__file__), "--_subprocess"] + [
            a for a in (argv if argv is not None else sys.argv[1:])
        ]
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=3000)
        sys.stderr.write(r.stderr[-8000:] if len(r.stderr) > 8000 else r.stderr)
        if r.returncode != 0:
            print(f"soak_fleet subprocess failed:\n{r.stdout[-2000:]}",
                  file=sys.stderr)
            return r.returncode
        line = r.stdout.strip().splitlines()[-1]
        json.loads(line)  # malformed output must fail loudly
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    os.environ.setdefault("THUNDER_TPU_RETRY_BACKOFF_S", "0")
    result = run_soak(args)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if soak_ok(result) else 1


if __name__ == "__main__":
    raise SystemExit(main())
