"""Microbenchmark: legacy flash vs splash attention on the bench shape.

B=2 H=32 T=2048 D=100 (open_llama_3b), causal, bf16.

Timing method: iterations are dependency-chained (the output feeds the next
input) so the device must serialize them, and we take the slope between a
short and a long run to cancel the axon tunnel's fixed ~95 ms round-trip.
"""
from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

B, H, T, D = 2, 32, 2048, 100
SCALE = 1.0 / (100 ** 0.5)


def chain_time(step, state, n_short=5, n_long=45):
    """step: state -> state (jitted). Returns per-iter seconds via slope."""
    s = step(state)
    jax.block_until_ready(s)

    def run(n):
        s = state
        t0 = time.perf_counter()
        for _ in range(n):
            s = step(s)
        jax.block_until_ready(s)
        return time.perf_counter() - t0

    run(2)
    t_s = run(n_short)
    t_l = run(n_long)
    return (t_l - t_s) / (n_long - n_short)


def flops_fwd():
    return 2 * 2 * B * H * T * T * D / 2


def legacy_flash(q, k, v, block=512):
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes, flash_attention

    sizes = BlockSizes(
        block_q=block, block_k_major=block, block_k=block, block_b=1,
        block_q_major_dkv=block, block_k_major_dkv=block, block_k_dkv=block, block_q_dkv=block,
        block_k_major_dq=block, block_k_dq=block, block_q_dq=block,
    )
    return flash_attention(q, k, v, causal=True, sm_scale=SCALE, block_sizes=sizes)


def make_splash(bq=512, bkv=512, bkv_compute=512, use_fused_bwd=True, bq_dkv=512, bkv_dkv=512):
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    mask = sm.MultiHeadMask([sm.CausalMask((T, T)) for _ in range(H)])
    block_sizes = sk.BlockSizes(
        block_q=bq, block_kv=bkv, block_kv_compute=bkv_compute,
        block_q_dkv=bq_dkv, block_kv_dkv=bkv_dkv, block_kv_dkv_compute=bkv_dkv,
        block_q_dq=None if use_fused_bwd else bq_dkv,
        block_kv_dq=None if use_fused_bwd else bkv_dkv,
        use_fused_bwd_kernel=use_fused_bwd,
    )
    kernel = sk.make_splash_mha(mask=mask, head_shards=1, q_seq_shards=1, block_sizes=block_sizes)

    def attn(q, k, v):
        return jax.vmap(kernel)(q * SCALE, k, v)

    return attn


def xla_attn(q, k, v):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * SCALE
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def run(name, attn_fn, q, k, v, check_against=None):
    @jax.jit
    def fwd_step(state):
        qq, out = state
        out = attn_fn(qq, k, v)
        # chain: next q depends on out but equals original q numerically-ish
        return qq + 0.0 * out, out

    def loss(qq, kk, vv):
        return jnp.sum(attn_fn(qq, kk, vv).astype(jnp.float32))

    gradf = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def bwd_step(state):
        qq, _ = state
        dq, dk, dv = gradf(qq, k, v)
        return qq + 0.0 * dq, dq

    state = (q, jnp.zeros_like(q))
    err = ""
    if check_against is not None:
        mine = np.asarray(attn_fn(q, k, v), dtype=np.float32)
        ref = np.asarray(check_against(q, k, v), dtype=np.float32)
        err = f" maxerr={np.abs(mine-ref).max():.3e}"
    try:
        t_fwd = chain_time(fwd_step, state)
    except Exception as e:
        print(f"{name:36s} FWD FAILED: {str(e)[:120]}")
        return
    try:
        t_bwd = chain_time(bwd_step, state)
    except Exception as e:
        print(f"{name:36s} fwd {t_fwd*1e3:7.2f}ms ({flops_fwd()/t_fwd/1e12:5.1f} TF/s)  BWD FAILED: {str(e)[:80]}")
        return
    print(f"{name:36s} fwd {t_fwd*1e3:7.2f}ms ({flops_fwd()/t_fwd/1e12:5.1f} TF/s)   fwd+bwd {t_bwd*1e3:7.2f}ms ({3.5*flops_fwd()/t_bwd/1e12:5.1f} TF/s){err}")


def main():
    global D
    if len(sys.argv) > 1:
        D = int(sys.argv[1])
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, H, T, D), dtype=jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, T, D), dtype=jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, T, D), dtype=jnp.bfloat16)
    print(f"shape B={B} H={H} T={T} D={D}; ideal causal fwd @197TF/s = {flops_fwd()/197e12*1e3:.2f}ms")

    run("legacy flash b512", legacy_flash, q, k, v, check_against=xla_attn)
    run("splash fused-bwd 512", make_splash(), q, k, v, check_against=xla_attn)
    run("splash fused-bwd bkv1024", make_splash(bq=512, bkv=1024, bkv_compute=512, bq_dkv=512, bkv_dkv=1024), q, k, v)
    run("splash fused-bwd 1024", make_splash(bq=1024, bkv=1024, bkv_compute=1024, bq_dkv=1024, bkv_dkv=1024), q, k, v)
    run("splash fused-bwd 2048", make_splash(bq=2048, bkv=2048, bkv_compute=2048, bq_dkv=2048, bkv_dkv=2048), q, k, v)
    run("splash split-bwd 512", make_splash(use_fused_bwd=False), q, k, v)
    run("xla materialized", xla_attn, q, k, v)


if __name__ == "__main__":
    main()
