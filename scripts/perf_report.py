#!/usr/bin/env python
"""Performance attribution reports and the bench regression gate.

Two modes:

**History / regression gate** — build the perf trajectory across committed
bench rounds and flag per-metric deltas beyond thresholds::

    python scripts/perf_report.py --history BENCH_r0*.json
    python scripts/perf_report.py --history BENCH_r0*.json --gate   # CI: exit 1
                                                                    # on un-acked regressions
    python scripts/perf_report.py --history MULTICHIP_BENCH_r*.json --gate
    python scripts/perf_report.py --history SOAK_r*.json --gate

The single-host (``BENCH_r*.json``, from ``bench.py``), multichip
(``MULTICHIP_BENCH_r*.json``, from ``scripts/bench_multichip.py``), and
soak (``SOAK_r*.json``, from ``scripts/soak_fleet.py`` — headline
``value`` is goodput tokens/sec, gated UP-good) series are gated
separately — one invocation per glob — with the same direction-aware
deltas, noise floors, and ack semantics.

Metric direction is inferred from the name (times/counts: lower is better;
MFU/throughput/ratios-vs-baseline: higher is better); sub-noise-floor
deltas on second-scale trace/compile timings are ignored. Known, accepted
regressions live in ``BENCH_ACK.json`` at the repo root (``--ack`` to point
elsewhere) so the gate stays green on history while failing loudly on new
regressions — the committed file acknowledges the r4→r5
``train_xla_compile_s`` 20.7s→43.3s jump this tool was built to catch.
``scripts/lint_traces.py`` runs the gate over the committed history.

**Attribution** — the measured/roofline report over a profile directory
(``thunder_tpu.profile()`` run under ``THUNDER_TPU_ANNOTATE_TRACES=1``)::

    python scripts/perf_report.py --trace-dir /tmp/prof --steps 3
    python scripts/perf_report.py --trace-dir /tmp/prof --model gpt-tiny \
        --batch 2 --seq 16        # join the static cost model → roofline/MFU

See docs/performance.md for the full profile → perf_report → roofline
workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# =============================================================================
# History / regression gate
# =============================================================================

# Direction inference: higher-is-better substrings win first (an MFU ratio
# name like train_synced_mfu_vs_ref_mfu must not fall through to the "_s"
# time suffix), then lower-is-better time/count shapes. Unmatched metrics are
# reported in the trajectory but never gated.
_HIGHER_SUBSTRINGS = ("mfu", "vs_baseline", "tokens_per_sec", "dots_passed",
                      "goodput", "achieved_frac", "coverage_pct")
_LOWER_SUFFIXES = ("_s", "_us", "_ms", "_pct", "_pct_static", "_seconds", "_ms_per_step")
_LOWER_EXACT = {"value", "recompile_count"}

# Absolute-delta floors (same units as the metric): second-scale pipeline
# timings jitter ±0.3s run to run; a 0.2s→0.3s "+50%" is noise, a
# 20.7s→43.3s "+109%" is not.
_NOISE_FLOORS = (
    ("trace_claim_s", 1.0),
    ("xla_compile_s", 2.0),
    ("lookup_us", 5.0),
    ("dispatch_us", 20.0),
    ("overhead_pct", 0.5),
    ("exposed_pct", 5.0),
)

# Series-aware floors for the MULTICHIP_BENCH rounds (headline metric name
# starts with "multichip"): tiny-model steps on an emulated 8-device CPU
# mesh jitter tens of ms — and MFU/tokens track the same measurement — so
# the floors are sized to that jitter WITHOUT weakening the single-host
# BENCH gate, whose metrics share these names. Checked before the generic
# table; "value" is the multichip headline (iter seconds).
_MULTICHIP_NOISE_FLOORS = (
    ("value", 0.02),
    ("iter_s", 0.02),
    ("synced_s", 0.02),
    ("strict_sync_s", 0.02),
    ("mfu", 5e-4),
    # tokens/sec is 256/iter_s: at the r03+ ~11ms step, the same ±1.5ms
    # scheduler jitter the iter floors absorb swings tokens by ±3000 —
    # the old 2000 floor (sized at r02's ~8k tok/s) gated pure noise.
    ("tokens_per_sec", 4000.0),
    # resilience_overhead_pct is a RATIO of two jittery tiny-step timings:
    # single-digit swings are measurement noise on the CPU mesh.
    ("overhead_pct", 5.0),
    # The snapshot stall is a host gather of a tiny model on a contended
    # CPU — a few ms of scheduler jitter is noise (ISSUE 14).
    ("stall_ms_per_step", 3.0),
    # Static exposed-collective % from the HLO auditor (ISSUE 16) is
    # deterministic given the HLO, but XLA fusion decisions wobble a little
    # across versions/flags; a couple of points is not a scheduling
    # regression.
    ("exposed_pct_static", 2.0),
)

# SOAK_r* rounds (headline metric "soak_goodput"): goodput on the emulated
# CPU mesh inherits the tiny-step jitter TWICE (ideal step AND soak wall
# clock share the scheduler), and the recovery path lengths vary with
# host load — the floors are sized to that, per the committed r01 noise
# measurement, without touching the bench series.
_SOAK_NOISE_FLOORS = (
    ("value", 800.0),              # goodput tokens/s
    ("tokens_per_sec", 800.0),
    ("goodput_ratio", 0.15),
    ("overhead_pct", 5.0),
    # Recovery seconds charged per fault: sized to r01's 3.61 s/fault scale
    # when committed; re-sized to the tiered-checkpoint era (ISSUE 14,
    # r02 ≈ 1.x s/fault) so the comparator keeps teeth.
    ("per_fault_s", 1.5),
    ("stall_ms_per_step", 3.0),    # snapshot stall under CPU-mesh jitter
    ("wall_s", 60.0),
    ("_s", 60.0),                  # any other second-scale soak timing
)

# SOAK_POD_r* rounds (headline "soak_pod_goodput", from scripts/soak_pod.py
# — ISSUE 18): same CPU-mesh jitter story as the fleet soak, plus the
# degraded-window split whose tokens/s rides on a handful of accum-rescaled
# steps. Checked BEFORE the generic soak table ("soak_pod" startswith
# "soak"); anything not listed here falls through to the soak floors.
_SOAK_POD_NOISE_FLOORS = (
    ("degraded_tokens_per_sec", 600.0),  # ~15-step window, double jitter
    ("goodput_ratio", 0.05),
    ("shrink_latency_s", 0.05),    # sub-second controller latencies: gate
    ("regrow_to_full_s", 2.0),     # on scale changes, not scheduler noise
)


# ROOFLINE_r* rounds (headline metric "roofline_*", from bench.py's
# roofline path — ISSUE 19): the per-op ``op_<line>_<sym>_us`` /
# ``_achieved_frac`` series. Per-op microsecond timings are the noisiest
# numbers the gate sees (single-op, single-probe, tens of µs on the CPU
# round) — the floors absorb scheduler jitter while still catching an op
# that genuinely doubled; achieved fraction is a ratio of the same
# measurement, floored absolutely.
_ROOFLINE_NOISE_FLOORS = (
    ("achieved_frac", 0.05),
    ("_us", 40.0),
    ("coverage_pct", 10.0),
    ("value", 0.2),                # total device-busy ms/step
)


# CRITPATH_r* rounds (headline "critpath_exposed_pct", from soak_pod.py's
# --critpath-out — ISSUE 20): the measured exposed-collective share is
# static-wire-priced against the MEASURED ideal step, so it inherits the
# CPU-mesh step jitter; skew recovery error is µs-scale in practice but
# rides two time.time() reads per barrier. The structural invariants
# (class coverage, host attribution, detector/citation joins) are gated
# absolutely in _critpath_failures, not by deltas.
_CRITPATH_NOISE_FLOORS = (
    ("value", 5.0),                # measured exposed %
    ("exposed_pct", 5.0),
    ("_pct", 5.0),
    ("recovery_err_ms", 10.0),
    ("_ms", 10.0),
    ("_s", 60.0),
)


def metric_direction(name: str, series: str = "") -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = not gated.
    ``series`` (the round's headline ``metric`` name) resolves the fields
    whose direction follows the series: the SOAK rounds' headline ``value``
    is goodput tokens/sec (up-good), where every other series' ``value`` is
    a time (down-good)."""
    low = name.lower()
    if series.lower().startswith("soak") and low == "value":
        return 1
    if any(s in low for s in _HIGHER_SUBSTRINGS):
        return 1
    if low in _LOWER_EXACT or low.endswith(_LOWER_SUFFIXES):
        return -1
    return None


def mfu_comparable(name: str, *rounds: dict) -> bool:
    """MFU against the ``cpu`` fallback spec is meaningless (the "peak
    FLOP/s" is a made-up host number — MULTICHIP_BENCH r02's 0.001) and
    would trip direction-aware gating the first time it wiggles: an MFU
    metric is only gated when every round that reports it ran on a real
    device spec."""
    if "mfu" not in name.lower():
        return True
    return all(m.get("_device_spec") != "cpu" for m in rounds)


def noise_floor(name: str, series: str = "") -> float:
    """Minimum absolute delta for ``name`` to gate; ``series`` is the
    round's headline ``metric`` name, selecting the multichip/soak floor
    tables for those rounds (the series share metric names)."""
    low = name.lower()
    if series.lower().startswith("multichip"):
        for suffix, floor in _MULTICHIP_NOISE_FLOORS:
            if low.endswith(suffix):
                return floor
    if series.lower().startswith("soak_pod"):
        for suffix, floor in _SOAK_POD_NOISE_FLOORS:
            if low.endswith(suffix):
                return floor
    if series.lower().startswith("soak"):
        for suffix, floor in _SOAK_NOISE_FLOORS:
            if low.endswith(suffix):
                return floor
    if series.lower().startswith("roofline"):
        for suffix, floor in _ROOFLINE_NOISE_FLOORS:
            if low.endswith(suffix):
                return floor
    if series.lower().startswith("critpath"):
        for suffix, floor in _CRITPATH_NOISE_FLOORS:
            if low.endswith(suffix):
                return floor
    for suffix, floor in _NOISE_FLOORS:
        if low.endswith(suffix):
            return floor
    return 0.0


# Headline fields whose meaning follows the round's "metric" name (r01's
# headline was the forward bench, r02+ the training bench): only comparable
# when consecutive rounds benched the same thing.
_HEADLINE_KEYS = {"value", "vs_baseline", "tokens_per_sec", "mfu", "baseline_mfu_a100"}


def load_round(path: str) -> tuple[str, dict[str, float]]:
    """(round label, numeric metrics) from one committed bench JSON — the
    driver's ``{"n", "cmd", "rc", "tail", "parsed": {...}}`` wrapper or a
    bare ``bench.py`` JSON line. The round's headline ``metric`` name is kept
    under ``_metric_name`` for the comparability check."""
    with open(path) as f:
        doc = json.load(f)
    metrics = doc.get("parsed", doc) if isinstance(doc, dict) else {}
    if not isinstance(metrics, dict):
        metrics = {}
    m = re.search(r"r(\d+)", os.path.basename(path))
    label = f"r{int(m.group(1)):02d}" if m else os.path.basename(path)
    out = {
        k: float(v)
        for k, v in metrics.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }
    if isinstance(metrics.get("metric"), str):
        out["_metric_name"] = metrics["metric"]  # type: ignore[assignment]
    if isinstance(metrics.get("device_spec"), str):
        out["_device_spec"] = metrics["device_spec"]  # type: ignore[assignment]
    return label, out


@dataclass
class Regression:
    metric: str
    frm: str
    to: str
    prev: float
    cur: float
    pct: float  # signed relative change
    acked: bool = False
    reason: str = ""

    @property
    def key(self) -> str:
        return f"{self.frm}->{self.to}:{self.metric}"

    def format(self) -> str:
        tag = "acked" if self.acked else "REGRESSION"
        note = f" ({self.reason})" if self.reason else ""
        return (
            f"{tag}: {self.metric} {self.prev:g} -> {self.cur:g} "
            f"({self.pct * 100:+.1f}%) over {self.frm}->{self.to}{note}"
        )


def load_ack(path: Optional[str]) -> dict[str, str]:
    """``{transition:metric -> reason}`` from a BENCH_ACK.json file."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    out: dict[str, str] = {}
    for entry in doc.get("acknowledged", []):
        out[f"{entry['transition']}:{entry['metric']}"] = entry.get("reason", "")
    return out


def analyze_history(
    rounds: list[tuple[str, dict[str, float]]],
    *,
    threshold: float = 0.10,
    ack: Optional[dict[str, str]] = None,
) -> list[Regression]:
    """Regressions across every consecutive round pair: a gated metric whose
    relative change exceeds ``threshold`` in the bad direction AND whose
    absolute delta clears the metric's noise floor."""
    ack = ack or {}
    out: list[Regression] = []
    for (l0, m0), (l1, m1) in zip(rounds, rounds[1:]):
        same_headline = m0.get("_metric_name") == m1.get("_metric_name")
        series = str(m0.get("_metric_name") or m1.get("_metric_name") or "")
        for name in sorted(set(m0) & set(m1)):
            direction = metric_direction(name, series)
            if direction is None:
                continue
            if name in _HEADLINE_KEYS and not same_headline:
                continue  # the rounds benched different headline workloads
            if not mfu_comparable(name, m0, m1):
                continue  # cpu-fallback MFU is not a real utilization number
            prev, cur = m0[name], m1[name]
            if prev == 0:
                continue
            pct = (cur - prev) / abs(prev)
            bad = pct > threshold if direction < 0 else pct < -threshold
            if not bad or abs(cur - prev) <= noise_floor(name, series):
                continue
            r = Regression(metric=name, frm=l0, to=l1, prev=prev, cur=cur, pct=pct)
            if r.key in ack:
                r.acked, r.reason = True, ack[r.key]
            out.append(r)
    return out


def compare_rounds(
    prev: dict[str, float], cur: dict[str, float], *, threshold: float = 0.10,
) -> tuple[dict[str, float], list[str]]:
    """One-transition comparison used by ``bench.py`` against the newest
    committed round: ``(deltas, regressions)`` where ``deltas`` maps each
    gated metric to its signed relative change and ``regressions`` holds
    human-readable strings for changes beyond ``threshold`` in the bad
    direction (noise floors applied)."""
    same_headline = prev.get("_metric_name") == cur.get("_metric_name")
    series = str(prev.get("_metric_name") or cur.get("_metric_name") or "")
    deltas: dict[str, float] = {}
    regs: list[str] = []
    for name in sorted(set(prev) & set(cur)):
        direction = metric_direction(name, series)
        if direction is None:
            continue
        if name in _HEADLINE_KEYS and not same_headline:
            continue
        if not mfu_comparable(name, prev, cur):
            continue
        p, c = prev[name], cur[name]
        if not isinstance(p, (int, float)) or not isinstance(c, (int, float)) or p == 0:
            continue
        pct = (c - p) / abs(p)
        deltas[name] = round(pct, 4)
        bad = pct > threshold if direction < 0 else pct < -threshold
        if bad and abs(c - p) > noise_floor(name, series):
            regs.append(f"{name} {p:g} -> {c:g} ({pct * 100:+.1f}%)")
    return deltas, regs


def format_history(rounds: list[tuple[str, dict[str, float]]],
                   regressions: list[Regression]) -> str:
    labels = [l for l, _ in rounds]
    series = str(next((m.get("_metric_name") for _, m in rounds
                       if m.get("_metric_name")), ""))
    names = sorted({n for _, m in rounds for n in m
                    if metric_direction(n, series) is not None})
    w = max((len(n) for n in names), default=10)
    lines = ["bench history: " + " -> ".join(labels),
             f"  {'metric':<{w}} " + " ".join(f"{l:>10}" for l in labels)]
    for n in names:
        cells = []
        for _, m in rounds:
            v = m.get(n)
            cells.append(f"{v:>10.4g}" if v is not None else f"{'-':>10}")
        arrow = {1: "^", -1: "v"}[metric_direction(n, series)]
        note = "" if mfu_comparable(n, *[m for _, m in rounds]) else \
            " (cpu spec: not comparable, not gated)"
        lines.append(f"  {n:<{w}} " + " ".join(cells) + f"  [{arrow}]{note}")
    if regressions:
        lines.append("")
        for r in regressions:
            lines.append("  " + r.format())
    else:
        lines.append("  no regressions beyond threshold")
    return "\n".join(lines)


def run_history_gate(
    paths: list[str],
    *,
    threshold: float = 0.10,
    ack_path: Optional[str] = None,
    gate: bool = False,
    out=sys.stdout,
) -> int:
    """The CI entry (also called by scripts/lint_traces.py): print the
    trajectory + flags; exit 1 only under ``--gate`` with un-acked
    regressions."""
    rounds = [load_round(p) for p in sorted(paths)]
    rounds = [(l, m) for l, m in rounds if m]
    if not rounds:
        print("perf_report --history: no rounds with metrics", file=out)
        return 0
    if len(rounds) < 2:
        # No trajectory to diff — but the newest round's ABSOLUTE
        # acceptance invariants (ops plane, pod federation) still gate:
        # the SOAK_POD series ships with a single committed round and its
        # pass/fail proofs must hold from r01 onward.
        print("perf_report --history: need at least two rounds with metrics "
              "to diff; checking absolute invariants only", file=out)
        failures = (_ops_plane_failures(rounds[-1]) + _pod_failures(rounds[-1])
                    + _roofline_failures(rounds[-1])
                    + _critpath_failures(rounds[-1]))
        if failures:
            print("\nperf_report: acceptance failed on the newest round: "
                  + ", ".join(failures), file=out)
        return 1 if (gate and failures) else 0
    if ack_path is None:
        repo_ack = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_ACK.json")
        ack_path = repo_ack
    regs = analyze_history(rounds, threshold=threshold, ack=load_ack(ack_path))
    print(format_history(rounds, regs), file=out)
    fresh = [r for r in regs if not r.acked]
    if fresh:
        print(
            f"\nperf_report: {len(fresh)} un-acknowledged regression(s) "
            f"(threshold {threshold * 100:.0f}%); acknowledge deliberate ones in "
            f"{os.path.basename(ack_path or 'BENCH_ACK.json')}",
            file=out,
        )
    ops_failures = (_ops_plane_failures(rounds[-1]) + _pod_failures(rounds[-1])
                    + _roofline_failures(rounds[-1])
                    + _critpath_failures(rounds[-1]))
    if ops_failures:
        print(
            "\nperf_report: acceptance failed on the newest "
            "round: " + ", ".join(ops_failures), file=out,
        )
    return 1 if (gate and (fresh or ops_failures)) else 0


def _ops_plane_failures(newest: tuple) -> list[str]:
    """Absolute ops-plane checks on the newest SOAK round (ISSUE 15) —
    unlike the direction-aware deltas, these are pass/fail invariants:
    every soak fault class with a streaming detector must have raised at
    least one anomaly, detection lead must be positive, and every
    timeout/halt must have produced a schema-valid flight-recorder dump.
    Rounds predating the ops plane (no soak_ops keys) are exempt."""
    label, m = newest
    if not str(m.get("_metric_name", "")).startswith("soak"):
        return []
    if "soak_undetected_detector_classes" not in m:
        return []  # pre-ops-plane round
    out = []
    for key in ("soak_undetected_detector_classes", "soak_flightrec_invalid",
                "soak_flightrec_missing"):
        v = m.get(key)
        if v:
            out.append(f"{label}: {key}={v:g}")
    lead = m.get("soak_detection_lead")
    if lead is not None and lead <= 0:
        out.append(f"{label}: soak_detection_lead={lead:g} (need > 0: an "
                   f"anomaly must precede the decision citing it)")
    return out


def _pod_failures(newest: tuple) -> list[str]:
    """Absolute federation checks on the newest SOAK_POD round (ISSUE 18)
    — the elastic shrink/regrow acceptance invariants, pass/fail
    regardless of how many rounds exist:

    - zero unrecovered faults, unactuated decisions, replay errors, and
      process restarts;
    - the fleet actually shrank (min width < full width, degraded steps
      ran) AND regrew to full DP width (final == full), with shrink and
      regrow decision counts equal — a flapping slice may not buy extra
      shrinks;
    - every slice-loss recovery restored from the cross-slice buddy's
      peer-RAM tier (nonpeer count 0) and disk served nothing after the
      step-0 anchor;
    - when the schedule carried the flap seam, its cooldown->lost
      re-failure edge is in the ledger (refailures >= 1); when it carried
      the slow-slice window, the DCN-tier spread detector raised at least
      one slice_spread anomaly."""
    label, m = newest
    if not str(m.get("_metric_name", "")).startswith("soak_pod"):
        return []
    out = []
    for key in ("soak_pod_unrecovered", "soak_pod_unactuated",
                "soak_pod_replay_errors", "soak_pod_restarts",
                "soak_pod_slice_loss_nonpeer_restores",
                "soak_pod_disk_restores_after_anchor"):
        v = m.get(key)
        if v:
            out.append(f"{label}: {key}={v:g}")
    full, final = m.get("soak_pod_full_width"), m.get("soak_pod_final_width")
    if full is not None and final != full:
        out.append(f"{label}: final_width={final:g} != full_width={full:g} "
                   f"(fleet did not regrow)")
    if full is not None and not (m.get("soak_pod_min_width", full) < full
                                 and m.get("soak_pod_degraded_steps", 0) > 0):
        out.append(f"{label}: no degraded window (the soak never actually "
                   f"lost a slice)")
    shrinks, regrows = m.get("soak_pod_shrinks"), m.get("soak_pod_regrows")
    if shrinks is not None and not (shrinks == regrows and shrinks > 0):
        out.append(f"{label}: shrinks={shrinks:g} regrows={regrows:g} "
                   f"(need equal and > 0)")
    if not m.get("soak_pod_slice_loss_restores"):
        out.append(f"{label}: soak_pod_slice_loss_restores=0 (no peer-tier "
                   f"recovery was proven)")
    if m.get("soak_pod_flap_injected") and \
            not m.get("soak_pod_flap_refailures"):
        out.append(f"{label}: flap injected but no cooldown->lost re-failure "
                   f"edge in the ledger")
    if m.get("soak_pod_slow_injected") and \
            not m.get("soak_pod_slice_spread_anomalies"):
        out.append(f"{label}: slow slice injected but no slice_spread "
                   f"anomaly was raised")
    return out


def _critpath_failures(newest: tuple) -> list[str]:
    """Absolute checks on the newest CRITPATH round (ISSUE 20) — the fleet
    critical-path ledger's acceptance invariants, pass/fail regardless of
    how many rounds exist:

    - the ledger folded a real run (>= 5 steps) and the per-step breakdown
      carried >= 5 distinct nonzero time classes, summing to ~1;
    - clock alignment is falsifiable and passed: the estimator recovered
      the run's injected per-slice offsets within 25 ms, with confidence
      >= 0.5 and no spurious outlier hosts (the soak injects clean skews);
    - straggler-wait is attributed to the seeded slow slice;
    - the detectors saw the shift (>= 1 bottleneck_shift anomaly) AND the
      autopilot cited it in >= 1 decision's evidence;
    - the static-vs-measured exposed-collective cross-check agrees within
      the 10-point noise band (on the emulated fleet the wire classes are
      static-priced, so a larger gap means the plumbing broke)."""
    label, m = newest
    if not str(m.get("_metric_name", "")).startswith("critpath"):
        return []
    out = []
    steps = m.get("critpath_steps", 0)
    if steps < 5:
        out.append(f"{label}: critpath_steps={steps:g} (need >= 5)")
    ncls = m.get("critpath_nonzero_classes", 0)
    if ncls < 5:
        out.append(f"{label}: critpath_nonzero_classes={ncls:g} "
                   f"(need >= 5 distinct time classes)")
    fsum = m.get("critpath_frac_sum")
    if fsum is not None and abs(fsum - 1.0) > 0.02:
        out.append(f"{label}: critpath_frac_sum={fsum:g} (breakdown must "
                   f"sum to ~1)")
    err = m.get("critpath_skew_recovery_err_ms")
    if err is None or not (err == err) or err > 25.0:
        out.append(f"{label}: critpath_skew_recovery_err_ms={err} "
                   f"(injected offsets not recovered within 25 ms)")
    conf = m.get("critpath_skew_min_confidence", 0.0)
    if conf < 0.5:
        out.append(f"{label}: critpath_skew_min_confidence={conf:g} "
                   f"(need >= 0.5)")
    if m.get("critpath_skew_outlier_hosts"):
        out.append(f"{label}: critpath_skew_outlier_hosts="
                   f"{m.get('critpath_skew_outlier_hosts'):g} (clean "
                   f"injected skews must not flag outliers)")
    if not m.get("critpath_straggler_host_match"):
        out.append(f"{label}: straggler-wait not attributed to the seeded "
                   f"slow slice")
    if not m.get("critpath_bottleneck_shift_anomalies"):
        out.append(f"{label}: no bottleneck_shift anomaly was raised")
    if not m.get("critpath_cited_decisions"):
        out.append(f"{label}: no autopilot decision cited bottleneck_shift")
    delta = m.get("critpath_delta_static_pct")
    if delta is None or abs(delta) > 10.0:
        out.append(f"{label}: critpath_delta_static_pct={delta} "
                   f"(static-vs-measured exposed pct disagree)")
    return out


def _roofline_failures(newest: tuple) -> list[str]:
    """Absolute checks on the newest ROOFLINE round (ISSUE 19) — the
    committed per-op series must stay a usable baseline regardless of how
    many rounds exist: at least 10 per-op rows, every row in the
    observability/roofline.py ``ROW_FIELDS`` schema (bench stamps
    ``roofline_schema_ok``), and at least 10 flattened
    ``op_*_achieved_frac`` keys so the per-op direction gate has ops to
    hold onto."""
    label, m = newest
    if not str(m.get("_metric_name", "")).startswith("roofline"):
        return []
    out = []
    rows = m.get("roofline_rows", 0)
    if rows < 10:
        out.append(f"{label}: roofline_rows={rows:g} (need >= 10 per-op rows)")
    if not m.get("roofline_schema_ok"):
        out.append(f"{label}: roofline_schema_ok="
                   f"{m.get('roofline_schema_ok', 0):g} (rows violate the "
                   f"ledger ROW_FIELDS schema)")
    n_flat = sum(1 for k in m
                 if k.startswith("op_") and k.endswith("_achieved_frac"))
    if n_flat < 10:
        out.append(f"{label}: only {n_flat} flattened op_*_achieved_frac "
                   f"key(s) (need >= 10 for the per-op gate)")
    return out


# =============================================================================
# Attribution mode
# =============================================================================


def run_attribution(
    trace_dir: str,
    *,
    steps: int = 1,
    top_k: int = 10,
    device: Optional[str] = None,
    hlo_path: Optional[str] = None,
    model: Optional[str] = None,
    batch: int = 2,
    seq: int = 16,
    out=sys.stdout,
) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from thunder_tpu.analysis.cost import cost_report
    from thunder_tpu.observability.attribution import attribute, join_cost_attribution

    hlo_text = None
    if hlo_path:
        with open(hlo_path) as f:
            hlo_text = f.read()
    try:
        attr = attribute(trace_dir, hlo_text=hlo_text)
    except FileNotFoundError as e:
        print(f"perf_report: {e}", file=sys.stderr)
        return 2

    cost = None
    if model:
        from thunder_tpu.core import dtypes
        from thunder_tpu.models import gpt as m

        cfg = m.name_to_config(model)
        params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
        idx = np.random.RandomState(0).randint(
            0, cfg.vocab_size, (batch, seq)).astype(np.int32)
        cost = cost_report(lambda p, i: m.forward(p, i, cfg), params, idx,
                           executors=["jax"], device=device)
    join = join_cost_attribution(attr, cost, steps=steps)
    print(join.format(top_k), file=out)
    if attr.coverage < 0.9 and attr.device_busy_us:
        print(
            f"\nperf_report: only {attr.coverage * 100:.1f}% of device time "
            "attributed — profile with THUNDER_TPU_ANNOTATE_TRACES=1, or pass "
            "--hlo <compiled.txt> to join raw HLO op names",
            file=out,
        )
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_report.py",
        description="Bench-history regression gate and profile attribution reports",
    )
    p.add_argument("--history", nargs="+", metavar="BENCH.json",
                   help="committed bench rounds to diff (BENCH_r*.json)")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative regression threshold (default 0.10)")
    p.add_argument("--ack", default=None,
                   help="acknowledgment file (default: repo-root BENCH_ACK.json)")
    p.add_argument("--gate", action="store_true",
                   help="exit 1 on un-acknowledged regressions (CI mode)")
    p.add_argument("--trace-dir", default=None,
                   help="profile dir (or one trace-events JSON) to attribute")
    p.add_argument("--steps", type=int, default=1,
                   help="steps the profile bracketed (scales totals per step)")
    p.add_argument("--top", type=int, default=10, help="rows in the top-k table")
    p.add_argument("--device", default=None,
                   help="device spec name for the cost model (v5e/v5p/v4/a100/cpu)")
    p.add_argument("--hlo", default=None,
                   help="compiled-HLO text file to map raw hlo_op names to scopes")
    p.add_argument("--model", default=None,
                   help="GPT config name to build the cost model from (e.g. gpt-tiny)")
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--seq", type=int, default=16)
    args = p.parse_args(argv)

    if args.history:
        return run_history_gate(
            args.history, threshold=args.threshold, ack_path=args.ack, gate=args.gate
        )
    if args.trace_dir:
        return run_attribution(
            args.trace_dir, steps=args.steps, top_k=args.top, device=args.device,
            hlo_path=args.hlo, model=args.model, batch=args.batch, seq=args.seq,
        )
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
