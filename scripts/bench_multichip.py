#!/usr/bin/env python
"""Multichip benchmark: the FSDP×TP training step, measured.

The distributed half of the bench story (single-host: ``bench.py``): run one
full training step (fw+bw+optimizer, ``parallel.build_train_step``) over an
n-device mesh — a virtual 8-device CPU mesh anywhere, real chips when the
process already owns them — and measure what ``MULTICHIP_r*.json`` never
recorded: per-step wall time under the three timing protocols, aggregate
MFU from the PR 5 cost model, per-collective device time split into
hidden-under-compute vs exposed-on-the-critical-path, and the compile-phase
decomposition of the multichip XLA compile.

Two workloads per run:

1. **FSDP×TP step** (SPMD partitioner inserts the collectives): step
   timings, MFU, and per-collective-family measured wire time from a
   profiled run (``observability.attribution`` classifies ``all-gather``/
   ``all-reduce``/... rows and computes the overlap split).
2. **Explicit-collective FSDP×TP step** (trace-level ``dist_prims`` under
   ``shard_map``): every collective carries an ``L<idx>.<sym>#<pass>``
   scope. The step runs unscheduled (measured lane table → per-class ICI
   calibration), then through the certificate-driven comm scheduler
   (``transforms/comm_schedule.py``), and the committed overlap table joins
   the scheduler's static per-site hidden/exposed prediction against the
   measured lane segmentation — ROADMAP item 2's overlap work, landed.

Output: one JSON line on stdout (the committed ``MULTICHIP_BENCH_r*.json``
series), consumed by ``scripts/perf_report.py --history
MULTICHIP_BENCH_r*.json [--gate]`` with the same direction-aware deltas and
noise floors as the single-host series. ``scripts/lint_traces.py
--multichip`` runs a reduced-iteration smoke of this bench in CI.

Usage::

    python scripts/bench_multichip.py                 # 8 devices, defaults
    python scripts/bench_multichip.py --devices 8 --iters 20 \
        --out MULTICHIP_BENCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


def mesh_factors(n: int) -> dict:
    """Factor n devices into fsdp × tp, fsdp-first (the ROADMAP item 2
    shape): 8 → fsdp4·tp2, 4 → fsdp2·tp2, 2 → fsdp2, odd → fsdp=n."""
    tp = 2 if n % 2 == 0 and n > 2 else 1
    return {"fsdp": n // tp, "tp": tp}


def _executors():
    """Default to the jax executor: Pallas kernels run in interpret mode on
    the CPU mesh (orders of magnitude slower, and not what multichip timing
    should measure). THUNDER_BENCH_EXECUTORS overrides, as in bench.py."""
    spec = os.environ.get("THUNDER_BENCH_EXECUTORS")
    if not spec:
        return ["jax"]
    return [s.strip() for s in spec.split(",") if s.strip()]


# =============================================================================
# Workload 1: FSDP×TP training step (SPMD partitioner collectives)
# =============================================================================


def bench_fsdp_tp(args, result: dict) -> None:
    import thunder_tpu as ttpu
    from thunder_tpu.analysis.cost import resolve_device_spec, trace_cost
    from thunder_tpu.api import _jax_cache_counts
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.observability.attribution import scope_map_of
    from thunder_tpu.parallel import build_train_step, make_mesh
    from thunder_tpu.parallel.sharding import gpt_param_specs

    n = args.devices
    factors = mesh_factors(n)
    mesh = make_mesh(**factors)
    cfg = m.name_to_config(args.model)
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    rng = np.random.RandomState(0)
    B = args.batch or max(2, 2 * factors["fsdp"])
    idx = rng.randint(0, cfg.vocab_size, (B, args.seq)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    specs = gpt_param_specs(cfg, mesh)

    jax_c0 = _jax_cache_counts()
    t0 = time.perf_counter()
    step, opt, extrace = build_train_step(
        cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=1e-3,
        executors=_executors(), donate=False, return_extrace=True,
    )
    trace_s = time.perf_counter() - t0

    # Static planner overhead on the multichip trace (ISSUE 10): liveness
    # plan + schedule certificate, timed so the new static_analysis compile
    # phase is visible in the committed multichip record like any other
    # phase. The recorded peak divides INPUT params by their PartitionSpecs;
    # intermediates have no trace-level sharding (the SPMD partitioner
    # decides), so the number is an upper bound on per-device HBM —
    # activations charged at global shape.
    t0 = time.perf_counter()
    try:
        from thunder_tpu.analysis import liveness as live_mod
        from thunder_tpu.analysis import schedule as sched_mod

        divisors = live_mod.arg_divisors_from_specs(extrace, specs, mesh=mesh)
        plan = live_mod.plan_liveness(
            extrace, arg_divisors=divisors, include_rows=False
        )
        sched_mod.stamp(extrace)
        predicted_peak = int(plan.peak_bytes)
    except Exception:
        predicted_peak = None
    static_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    p, o, loss = step(params, opt, idx, tgt)
    loss.block_until_ready()
    compile_s = trace_s + time.perf_counter() - t0
    jax_c1 = _jax_cache_counts()
    loss0 = float(np.asarray(loss))
    assert np.isfinite(loss0), loss0

    # Async chain: iters steps threaded through the returned state, one sync.
    for _ in range(2):
        p, o, loss = step(p, o, idx, tgt)
    loss.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        p, o, loss = step(p, o, idx, tgt)
    loss_last = float(np.asarray(loss))
    iter_s = (time.perf_counter() - t0) / args.iters

    # Synced: every loss reaches the host before the next dispatch overlap
    # (bench.py's protocol); strict: hard block per step.
    n_sync = max(3, args.iters // 2)
    t0 = time.perf_counter()
    prev = None
    for _ in range(n_sync):
        p, o, loss = step(p, o, idx, tgt)
        if prev is not None:
            float(np.asarray(prev))
        prev = loss
    float(np.asarray(prev))
    synced_s = (time.perf_counter() - t0) / n_sync
    t0 = time.perf_counter()
    for _ in range(n_sync):
        p, o, loss = step(p, o, idx, tgt)
        loss.block_until_ready()
    strict_s = (time.perf_counter() - t0) / n_sync
    assert np.isfinite(loss_last), loss_last

    if args.resilience_overhead:
        # Steady-state cost of the mesh-wide fault-tolerance layer (ISSUE 9):
        # each guarded dispatch runs under the collective watchdog (worker
        # thread + join) and the step's new state through the SDC
        # replica-checksum guard. Target <2% at production step times;
        # docs/robustness.md documents the knobs (check_every amortizes the
        # checksum; fully-sharded leaves cost nothing).
        from thunder_tpu.resilience.watchdog import SDCGuard, guard_call

        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        guard = SDCGuard(check_every=1)
        # The guard's added work is strictly additive to a step (the
        # watchdog adds one worker-thread spawn+join per dispatch; the SDC
        # check runs on the host after the step syncs), so each component
        # is measured DIRECTLY and the overhead derived over the median
        # guarded step — an emulated CPU mesh's steps jitter ±50% under a
        # shared scheduler, which drowns any loop-vs-loop delta of a
        # percent-scale cost (the failed protocol r02 replaced).
        plain, checks = [], []
        for _ in range(max(6, n_sync)):
            t0 = time.perf_counter()
            p, o, loss = guard_call(step, (p, o, idx, tgt),
                                    fn_name="train_step", timeout_s=120.0)
            loss.block_until_ready()
            tc = time.perf_counter()
            plain.append(tc - t0)
            guard.check_state((p, o))
            checks.append(time.perf_counter() - tc)
        spawn = []
        noop = lambda: None  # noqa: E731
        for _ in range(50):
            t0 = time.perf_counter()
            guard_call(noop, (), fn_name="noop", timeout_s=120.0)
            spawn.append(time.perf_counter() - t0)
        step_s, check_s, spawn_s = med(plain), med(checks), med(spawn)
        overhead_pct = ((check_s + spawn_s) / step_s * 100.0) if step_s else 0.0
        result["resilience_iter_s"] = round(step_s + check_s + spawn_s, 4)
        result["resilience_overhead_pct"] = round(overhead_pct, 2)
        result["sdc_check_us_per_step"] = round(check_s * 1e6, 1)
        result["watchdog_dispatch_us"] = round(spawn_s * 1e6, 1)
        _log(f"resilience overhead: sdc check {check_s * 1e6:.0f}us + watchdog "
             f"{spawn_s * 1e6:.0f}us over a {step_s * 1e3:.1f}ms median step "
             f"= {overhead_pct:+.2f}%")

        # Tiered-checkpoint hot-path stall (ISSUE 14): the device→host
        # snapshot of the full train state (params + opt) — the ONLY cost
        # a snapshot_every=1 cadence would add to each step; the disk
        # protocol rides the background writer. Contrast with the
        # synchronous save the pre-tiered path paid at every cadence hit.
        import tempfile

        from thunder_tpu.resilience.preemption import CheckpointManager
        from thunder_tpu.resilience.snapshot import SnapshotStore

        import shutil

        ck_dir = tempfile.mkdtemp(prefix="ttpu_bench_ck_")
        try:
            store = SnapshotStore(host=0, ring=2)
            SnapshotStore.pair(store, SnapshotStore(host=1, ring=2))
            cmgr = CheckpointManager(ck_dir, backoff_s=0, store=store,
                                     async_flush=True)
            stalls = []
            for i in range(6):
                t0 = time.perf_counter()
                cmgr.snapshot((p, o), i)
                stalls.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            cmgr.save((p, o), 99)
            sync_save_s = time.perf_counter() - t0
            cmgr.close()
        finally:
            shutil.rmtree(ck_dir, ignore_errors=True)
        stall_ms = med(stalls) * 1e3
        result["checkpoint_stall_ms_per_step"] = round(stall_ms, 3)
        result["checkpoint_sync_save_ms"] = round(sync_save_s * 1e3, 2)
        _log(f"checkpoint tiers: snapshot stall {stall_ms:.2f}ms "
             f"(replicated to buddy) vs {sync_save_s * 1e3:.0f}ms "
             f"synchronous save")

    # Aggregate MFU: the traced program computes the GLOBAL batch, so its
    # FLOPs divide across every chip — MFU is flops / (t · n · per-chip peak).
    spec = resolve_device_spec(args.device_spec)
    cost = trace_cost(extrace, spec)
    mfu = cost.total_flops / (iter_s * n * spec.peak_flops["bf16"]) if iter_s else 0.0

    _log(f"fsdp_tp mesh={factors} B={B} T={args.seq} compile {compile_s:.1f}s "
         f"iter {iter_s * 1e3:.1f}ms (synced {synced_s * 1e3:.1f}ms, strict "
         f"{strict_s * 1e3:.1f}ms) loss {loss0:.3f}->{loss_last:.3f} "
         f"MFU {mfu * 100:.2f}% [{spec.name} x{n}]")

    result.update({
        "metric": "multichip_fsdp_tp_train_iter",
        "value": round(iter_s, 4),
        "unit": "s",
        "n_devices": n,
        "mesh": factors,
        "model": args.model,
        "batch": B,
        "seq": args.seq,
        "train_iter_s": round(iter_s, 4),
        "train_iter_synced_s": round(synced_s, 4),
        "train_iter_strict_sync_s": round(strict_s, 4),
        "train_tokens_per_sec": round(B * args.seq / iter_s) if iter_s else 0,
        "train_mfu": round(mfu, 5),
        "device_spec": spec.name,
        "train_flops_per_step": cost.total_flops,
        "multichip_trace_claim_s": round(trace_s, 2),
        "multichip_xla_compile_s": round(compile_s, 2),
        "compile_phases": {
            "trace_claim_s": round(trace_s, 2),
            "static_analysis_s": round(static_s, 3),
            "predicted_peak_bytes": predicted_peak,
            "xla_backend_compile_s": round(
                jax_c1["backend_compile_s"] - jax_c0["backend_compile_s"], 2),
            "persistent_cache_get_s": round(
                jax_c1["cache_get_s"] - jax_c0["cache_get_s"], 2),
            "persistent_cache_hits": jax_c1["hits"] - jax_c0["hits"],
            "persistent_cache_misses": jax_c1["misses"] - jax_c0["misses"],
        },
    })

    # Static HLO audit of the compiled step executable (ISSUE 16): the
    # SPMD-partitioner-inserted collectives recovered from the compiled-HLO
    # text, classified per family, priced at the ring factors, and
    # schedule-analyzed — no profiler needed. The committed
    # spmd_collective_exposed_pct_static is the STATIC base the measured
    # spmd_collective_exposed_pct lane number is judged against, and the
    # baseline ROADMAP item 3's scheduling-hints work must move.
    t0 = time.perf_counter()
    try:
        from thunder_tpu.analysis.hlo_audit import audit_jitted

        hrep = audit_jitted(step, p, o, idx, tgt, device=spec)
        hrep.audit_s = time.perf_counter() - t0
        result["spmd_collective_exposed_pct_static"] = round(hrep.exposed_pct, 2)
        result["hlo_inserted_collectives"] = hrep.inserted_collectives
        result["hlo_static_collectives"] = {
            fam: {
                "count": agg["count"],
                "wire_bytes": int(agg["wire_bytes"]),
                "inserted": agg["inserted"],
            }
            for fam, agg in sorted(hrep.by_family.items())
        }
        result["compile_phases"]["hlo_audit_s"] = round(hrep.audit_s, 3)
        # Per-tier split of the audited wire (ISSUE 20): collectives whose
        # group fits inside one model-parallel block are charged to the
        # ICI tier, wider ones to DCN — the fleet timeline's static input
        # for its exposed-ICI/exposed-DCN critical-path classes
        # (observability/timeline.split_static_wire).
        from thunder_tpu.observability.timeline import split_static_wire

        tier = split_static_wire(hrep.sites, factors["tp"])
        result["hlo_wire_ici_us_static"] = round(tier["ici_us"], 2)
        result["hlo_wire_dcn_us_static"] = round(tier["dcn_us"], 2)
        result["hlo_wire_ici_frac_static"] = round(tier["ici_frac"], 4)
        _log(f"hlo audit: {hrep.n_ops} ops, {len(hrep.sites)} collectives "
             f"({hrep.inserted_collectives} partitioner-inserted), static "
             f"exposed {result['spmd_collective_exposed_pct_static']}% in "
             f"{hrep.audit_s:.2f}s: "
             + ", ".join(f"{f}={a['count']}" for f, a in sorted(hrep.by_family.items())))
    except Exception as e:  # noqa: BLE001 — the auditor is advisory here too
        _log(f"hlo audit failed (advisory): {type(e).__name__}: {e}")

    # Profiled run → per-collective measured wire time + overlap split.
    if not args.no_profile:
        import tempfile

        trace_dir = tempfile.mkdtemp(prefix="thunder_mc_prof_")
        try:
            scope_map = scope_map_of(step, p, o, idx, tgt)
        except Exception:
            scope_map = {}
        res = ttpu.profile(lambda: step(p, o, idx, tgt), trace_dir=trace_dir,
                           steps=args.profile_steps, warmup=1)
        if res["profiler"]:
            from thunder_tpu.observability.attribution import attribute

            attr = attribute(trace_dir, extra_scope_map=scope_map or None)
            steps = args.profile_steps
            coll = {
                cls: {
                    "us_per_step": round(row.us / steps, 1),
                    "hidden_us_per_step": round(row.hidden_us / steps, 1),
                    "exposed_us_per_step": round(row.exposed_us / steps, 1),
                    "calls": row.count,
                }
                for cls, row in sorted(attr.collective_summary().items())
            }
            busy = attr.device_busy_us / steps
            exposed = attr.exposed_collective_us / steps
            result["collectives"] = coll
            result["device_busy_us_per_step"] = round(busy, 1)
            result["collective_us_per_step"] = round(attr.collective_us / steps, 1)
            # Raw lane measurement of the SPMD (partitioner-inserted)
            # collectives. The committed headline collective_exposed_pct
            # moved to the explicit-collective workload at r03, where the
            # trace-level scheduler can actually prove hiding — this keeps
            # the r01/r02 measurement series alive under its own name.
            result["spmd_collective_exposed_pct"] = round(
                exposed / busy * 100.0, 2) if busy else 0.0
            _log(f"collectives: {attr.collective_us / steps:.0f}us/step on the wire "
                 f"({result['spmd_collective_exposed_pct']}% of device time exposed): "
                 + ", ".join(f"{c}={v['us_per_step']}us" for c, v in coll.items()))
        else:
            _log("profiler unavailable: collective attribution skipped")


# =============================================================================
# Workload 2: explicit-collective FSDP step (predicted vs measured overlap)
# =============================================================================


def bench_overlap(args, result: dict) -> None:
    """Explicit-collective FSDP×TP step through the comm scheduler (ISSUE 13).

    A K-layer fw+bw step whose collectives are trace-level ``dist_prims``
    under ``shard_map`` on the fsdp×tp mesh: per layer an fsdp
    ``synchronize`` gathers the sharded weight and a tp ``all_reduce``
    combines the partial activations; the grad transform emits the
    ``reduce_scatter``s. The run:

    1. stages + profiles the UNSCHEDULED trace (lane-segmentation table);
    2. fits an effective per-class ICI bandwidth from that measured table
       (``analysis.cost.calibrate_ici`` — the emulated mesh measures
       ~1000× the datasheet wire time, all rendezvous) so the scheduler's
       placement decisions are priced in the right order of magnitude;
    3. runs ``transforms/comm_schedule.schedule_collectives`` with the
       calibrated spec, restages, and profiles the SCHEDULED trace;
    4. joins the scheduler's static per-site hidden/exposed prediction
       (datasheet pricing — what real chips' latency-hiding scheduler
       realizes) against the measured lane table, per site.

    The committed headline ``collective_exposed_pct`` is the static
    prediction over the scheduled trace (exposed wire / total wire at the
    bench device spec); ``collective_exposed_pct_measured_lanes`` keeps the
    raw lane measurement, which is structurally ~100% exposed on the
    emulated CPU mesh (serial lanes — see docs/performance.md)."""
    import tempfile

    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import thunder_tpu as ttpu
    import thunder_tpu.clang as clang
    from thunder_tpu.analysis import schedule as sched_mod
    from thunder_tpu.analysis.cost import (
        calibrate_ici,
        collective_sym_class,
        resolve_device_spec,
        trace_cost,
    )
    from thunder_tpu.core.pytree import tree_flatten
    from thunder_tpu.distributed import prims as dist
    from thunder_tpu.distributed.runtime import (
        compile_with_collectives,
        stage_collective_trace,
    )
    from thunder_tpu.observability.attribution import attribute, parse_scope
    from thunder_tpu.parallel import make_mesh
    from thunder_tpu.transforms.comm_schedule import schedule_collectives

    n = args.devices
    factors = mesh_factors(n)
    fsdp_g, tp_g = factors["fsdp"], factors["tp"]
    mesh = make_mesh(**factors)
    rng = np.random.RandomState(0)
    layers, d, B = 4, 256, 64
    ws = [rng.randn(d, d).astype(np.float32) * (1.0 / np.sqrt(d))
          for _ in range(layers)]
    x = rng.randn(B, d).astype(np.float32)

    def loss_traced(*flat_in):
        *w_shards, xv = flat_in
        h = xv
        for w_shard in w_shards:
            w_full = dist.synchronize(w_shard, "fsdp", fsdp_g, "fsdp")
            h = clang.matmul(h, clang.transpose(w_full, 0, 1))
            if tp_g > 1:
                # avg: the identity on replicated activations, but the real
                # tp wire pattern (and its grad all_reduce) in the trace.
                h = dist.all_reduce(h, "tp", tp_g, op="avg")
            h = clang.tanh(h)
        return clang.mean(clang.mul(h, h))

    # Trace on per-device shard shapes; call with the global arrays —
    # shard_map's in_specs do the splitting (tests/_dist_worker.py idiom).
    shards = tuple(w[: d // fsdp_g] for w in ws)
    w_spec = P("fsdp", None)
    in_specs = tuple([w_spec] * layers + [P()])
    out_specs = (P(), tuple([w_spec] * layers + [P()]))
    jf0, extrace = compile_with_collectives(
        loss_traced, shards + (x,), mesh, in_specs, out_specs, grad=True,
    )
    flat = [jnp.asarray(a) for a in (*ws, x)]
    tree_flatten(jf0(*flat))[0][0].block_until_ready()

    def _profile(jf, tag):
        trace_dir = tempfile.mkdtemp(prefix=f"thunder_mc_overlap_{tag}_")
        res = ttpu.profile(lambda: jf(*flat), trace_dir=trace_dir,
                           steps=args.profile_steps, warmup=1)
        if not res["profiler"]:
            return None
        hlo_text = None
        try:
            # The watchdog wrapper around the jitted fn delegates lower.
            if hasattr(jf, "lower"):
                hlo_text = jf.lower(*flat).compile().as_text()
        except Exception:
            hlo_text = None
        return attribute(trace_dir, hlo_text=hlo_text)

    spec = resolve_device_spec(args.device_spec)
    steps = max(1, args.profile_steps)

    def _measured_by_line(attr):
        """{trace line: (measured us/step, lane-hidden us/step)} for the
        scoped collective rows of one profile."""
        out = {}
        if attr is None:
            return out
        for key, row in attr.collectives.items():
            ref = parse_scope(key)
            if ref is not None:
                got = out.setdefault(ref.line, [0.0, 0.0])
                got[0] += row.us / steps
                got[1] += row.hidden_us / steps
        return out

    # -- 1+2: unscheduled profile → per-class ICI calibration -----------------
    attr0 = _profile(jf0, "unsched")
    cost0 = trace_cost(extrace, spec)
    meas0 = _measured_by_line(attr0)
    samples = []
    for r in cost0.rows:
        if r.kind != "collective" or not r.comm_bytes:
            continue
        m = meas0.get(r.index)
        if m and m[0] > 0:
            samples.append((collective_sym_class(r.sym), r.comm_bytes, m[0] / 1e6))
    calibrated = calibrate_ici(spec, samples)
    if calibrated.ici_class_bw:
        result["ici_calibration"] = {
            "source": ("fitted from this run's measured per-collective table "
                       "(unscheduled profile, lane segmentation)"),
            "datasheet_ici_bw": spec.ici_bw,
            "effective_bw_by_class": {
                k: round(v, 1) for k, v in calibrated.ici_class_bw.items()
            },
        }
        _log("ici calibration: " + ", ".join(
            f"{k}={v / 1e6:.2f}MB/s (datasheet {spec.ici_bw / 1e9:.0f}GB/s)"
            for k, v in calibrated.ici_class_bw.items()))

    # -- 3: schedule with calibrated wire prices, restage, re-profile ---------
    scheduled, srep = schedule_collectives(extrace, device=calibrated)
    if srep is not None:
        for line in srep.format().splitlines():
            _log(line)
        result["comm_schedule"] = {
            k: v for k, v in srep.to_tag().items() if k != "sites"
        }
    jf1 = stage_collective_trace(scheduled, mesh, in_specs, out_specs)
    tree_flatten(jf1(*flat))[0][0].block_until_ready()
    attr1 = _profile(jf1, "sched")
    meas1 = _measured_by_line(attr1)

    # -- 4: static per-site prediction joined against measured lanes ----------
    pred_before = sched_mod.predict_overlap(extrace, device=spec)
    pred_after = sched_mod.predict_overlap(scheduled, device=spec)
    cost1 = trace_cost(scheduled, calibrated)
    cal_wire = {r.index: r.roofline_s * 1e6 for r in cost1.rows
                if r.kind == "collective"}
    moves = {}
    if srep is not None:
        moves = {s.key: s for s in srep.sites}

    rows = []
    for so in sorted(pred_after.sites, key=lambda s: -s.wire_us):
        m = meas1.get(so.index, (None, None))
        mv = moves.get(so.key)
        rows.append({
            "collective": so.label(),
            "class": collective_sym_class(so.sym) or so.sym,
            "axis": so.axis,
            "moved_from": mv.index_before if mv and mv.moved else None,
            "predicted_wire_us": round(so.wire_us, 2),
            "predicted_wire_us_calibrated": round(cal_wire.get(so.index, 0.0), 1),
            "predicted_hidden_us": round(so.hidden_us, 2),
            "predicted_exposed_us": round(so.exposed_us, 2),
            "window_us": round(so.window_us, 2),
            "measured_us_per_step": round(m[0], 1) if m[0] is not None else None,
            "measured_hidden_lane_us_per_step": (
                round(m[1], 1) if m[1] is not None else None
            ),
        })

    # No silent caps: the committed table is top-k by predicted wire, with
    # the drop recorded and logged (ISSUE 13 satellite).
    k = max(1, args.overlap_top_k)
    result["overlap"] = rows[:k]
    result["overlap_sites_total"] = len(rows)
    result["overlap_sites_shown"] = min(k, len(rows))
    result["overlap_sites_dropped"] = max(0, len(rows) - k)
    if result["overlap_sites_dropped"]:
        _log(f"overlap table: showing {k} of {len(rows)} collective sites "
             f"({result['overlap_sites_dropped']} dropped; --overlap-top-k raises)")

    # Headline: the scheduled trace's static exposed fraction of total wire
    # at the bench device spec — the compile-time twin real chips realize
    # via the latency-hiding scheduler. The raw lane measurement stays
    # alongside (serial CPU lanes cannot overlap, so it reads ~100%).
    result["collective_exposed_pct"] = round(pred_after.exposed_pct, 2)
    result["collective_exposed_pct_unscheduled"] = round(pred_before.exposed_pct, 2)
    result["collective_exposed_basis"] = (
        "static schedule prediction (exposed wire / total wire at "
        f"device_spec={spec.name}) over the comm-scheduled trace; per-site "
        "join vs measured lanes in 'overlap'"
    )
    if attr1 is not None and attr1.device_busy_us:
        result["collective_exposed_pct_measured_lanes"] = round(
            attr1.exposed_collective_us / attr1.device_busy_us * 100.0, 2
        )
    # Renamed from r02's overlap_predicted_comm_s: the workload changed at
    # r03 (2-layer fsdp MLP -> 4-layer fsdp4·tp2 step), so the old key's
    # wire volume is not comparable and must not gate.
    result["overlap_predicted_wire_s"] = round(cost0.comm_s, 6)
    _log(f"overlap: static exposed {pred_before.exposed_pct:.1f}% -> "
         f"{pred_after.exposed_pct:.1f}% of wire after scheduling "
         f"({srep.moves if srep else 0} moves)")


# =============================================================================
# Driver
# =============================================================================


def run(args) -> dict:
    result: dict = {}
    bench_fsdp_tp(args, result)
    try:
        bench_overlap(args, result)
    except Exception as e:
        # The overlap workload is diagnostic; its failure must not lose the
        # timing series. The error is recorded so the smoke can assert on it.
        _log(f"overlap workload failed ({type(e).__name__}: {e})")
        result["overlap_error"] = f"{type(e).__name__}: {e}"
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_multichip.py",
        description="FSDP×TP multichip training-step benchmark (MULTICHIP_BENCH series)",
    )
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--model", default="llama-tiny")
    p.add_argument("--batch", type=int, default=0, help="global batch (0 = auto)")
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--profile-steps", type=int, default=3)
    p.add_argument("--overlap-top-k", type=int, default=16,
                   help="rows committed in the per-site overlap table (the "
                        "total/dropped site counts are always recorded — no "
                        "silent caps)")
    p.add_argument("--no-profile", action="store_true")
    p.add_argument("--resilience-overhead", action="store_true",
                   help="also measure watchdog+SDC-guard steady-state step "
                        "overhead vs the strict protocol (ISSUE 9; target <2%%)")
    p.add_argument("--device-spec", default=None,
                   help="cost-model device spec (default: autodetect)")
    p.add_argument("--out", default=None, help="also write the JSON to this path")
    p.add_argument("--_subprocess", action="store_true", help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    import jax

    if len(jax.devices()) < args.devices and not args._subprocess:
        # Backend already initialized with fewer devices: re-exec on a
        # virtual CPU mesh (same pattern as __graft_entry__.dryrun_multichip).
        import subprocess

        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={args.devices}",
            "THUNDER_TPU_ANNOTATE_TRACES": os.environ.get("THUNDER_TPU_ANNOTATE_TRACES", "1"),
        }
        for k in ("THUNDER_BENCH_EXECUTORS", "THUNDER_TPU_EVENTS", "THUNDER_TPU_METRICS"):
            if os.environ.get(k):
                env[k] = os.environ[k]
        cmd = [sys.executable, os.path.abspath(__file__), "--_subprocess"] + [
            a for a in (argv if argv is not None else sys.argv[1:])
        ]
        r = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=1200)
        sys.stderr.write(r.stderr[-4000:] if len(r.stderr) > 4000 else r.stderr)
        if r.returncode != 0:
            print(f"bench_multichip subprocess failed:\n{r.stdout[-2000:]}", file=sys.stderr)
            return r.returncode
        line = r.stdout.strip().splitlines()[-1]
        json.loads(line)  # malformed output must fail loudly
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    # Annotated codegen so collective trace lines carry scopes in profiles.
    os.environ.setdefault("THUNDER_TPU_ANNOTATE_TRACES", "1")
    from thunder_tpu.api import _ensure_runtime

    _ensure_runtime()
    result = run(args)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
