#!/usr/bin/env python
"""Pod soak: federated slice-failure abuse with a committed goodput number.

The falsifiable half of ISSUE 18: run the DP-federated GPT workload on the
virtual mesh as ``--slices`` emulated ICI slices over a DCN tier, and
script the four slice seams through one run — a whole-slice loss, a DCN
partition, a slow slice, a flapping slice — with the fleet controller
(``resilience/federation.py``) deciding every shrink/regrow through the
autopilot. The run must end back at FULL width with zero unrecovered
faults, zero unactuated decisions, and NO process restart; its headline is
the same goodput shape as the fleet soak::

    goodput = (useful_tokens / wall_s) x (1 - resilience_overhead_pct/100)

with the degraded-mode window accounted honestly: while shrunk, the
survivors pay the loss-equivalent gradient-accumulation rescale
(``ceil(accum x W / w)`` micro-steps per optimizer step), so the measured
degraded tokens/s really is lower — reduced throughput, unchanged global
batch.

Acceptance invariants proven from the replayed event ledger (and gated by
``scripts/perf_report.py --history SOAK_POD_r*.json --gate``):

- every slice-loss recovery restored from the cross-slice buddy's PEER-RAM
  tier (``restore`` events ``tier="peer"``) — disk is touched only by the
  step-0 durability anchor;
- the flapping slice cost exactly one ``shrink_dp`` and one deferred
  ``regrow_dp`` (its cooldown->lost re-failure edge is in the ledger, and
  the decision count did not grow);
- the fleet regrew to full DP width without a process restart;
- the slow slice raised a ``slice_spread`` anomaly (the DCN-tier spread
  detector) that fed the autopilot's strike ledger.

Output: one JSON line (the committed ``SOAK_POD_r*.json`` series).
``scripts/lint_traces.py --federation`` runs the ``--smoke`` shape in CI.

Usage::

    python scripts/soak_pod.py                            # 60 steps, seed 1
    python scripts/soak_pod.py --steps 60 --seed 1 --out SOAK_POD_r01.json
    python scripts/soak_pod.py --smoke                    # CI-sized run
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr)


# =============================================================================
# The scripted slice-seam schedule
# =============================================================================


def make_spec(args) -> str:
    """The chaos spec for one pod soak — exact-step slice seams, so the
    episode structure (loss -> regrow -> partition -> slow window -> flap)
    is deterministic per seed and the gate can count episodes exactly.

    Full shape (``--steps`` >= 40): a whole-slice loss in the first third,
    a DCN partition at the midpoint (healing after ``heal`` steps while
    training continues in-slice), a count-limited slow window on slice 1
    (always active — the spread detector must flag it, and the fleet
    timeline's straggler-band ``bottleneck_shift`` must name it), and a
    flap at the two-thirds mark. The slow window sits on the SAME slice
    the loss takes out and covers the loss step: the critical-path ledger
    had already measured that slice dragging the fleet, so its
    ``bottleneck_shift`` verdict is the newest host-matched evidence in
    the ring when the ``slice_loss`` decision lands — the ISSUE 20
    citation join. Smoke shape: the slice loss alone — one scripted
    loss, shrink -> degraded training -> regrow, CI-sized."""
    loss_at = max(3, args.steps // 4)
    if args.smoke:
        return f"slice_loss@{loss_at},slice=1;seed={args.seed}"
    part_at = max(loss_at + args.recover_after + 6, args.steps // 2)
    flap_at = max(part_at + 6, (2 * args.steps) // 3)
    heal = 4
    slow_n = loss_at + 3  # count-limited: covers every step up to the loss
    return (
        f"slice_loss@{loss_at},slice=1"
        f";dcn_partition@{part_at}~{heal}"
        f";slice_slow@slice=1~{args.slow_delay_s}*{slow_n}"
        f";slice_flap@{flap_at},slice=1"
        f";seed={args.seed}"
    )


def _measure_pod_overheads(step_fn, state, *, snapshot_every: int, n: int = 6):
    """(ideal step seconds, resilience_overhead_pct) for the FEDERATED
    driver: its steady-state resilience tax is the cross-slice snapshot
    pipeline (host copy + checksum + buddy replication every
    ``snapshot_every`` steps), not the fleet soak's SDC guard — the pod
    driver runs no guard, and recovery/rebuild time is already inside the
    soak wall clock. Measured directly (median-vs-median, same reasoning
    as ``soak_fleet._measure_overheads``: loop deltas drown in CPU-mesh
    jitter) against a scratch 2-store ring so the real ring stays clean."""
    from thunder_tpu.resilience.snapshot import (
        Snapshot, SnapshotStore, pytree_crc32, to_host)

    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    steps = []
    for _ in range(max(4, n)):
        t0 = time.perf_counter()
        state, _ = step_fn(state)
        steps.append(time.perf_counter() - t0)
    scratch = [SnapshotStore(host=i, ring=2) for i in range(2)]
    SnapshotStore.make_ring(scratch)
    snaps = []
    for i in range(4):
        t0 = time.perf_counter()
        host_state = to_host(state)
        scratch[0].put(Snapshot(step=i, state=host_state,
                                crcs=pytree_crc32(host_state)))
        snaps.append(time.perf_counter() - t0)
    step_s, snap_s = med(steps), med(snaps)
    per_step = snap_s / max(1, snapshot_every)
    overhead_pct = (per_step / step_s * 100.0) if step_s else 0.0
    return step_s, overhead_pct, state


# =============================================================================
# The pod run
# =============================================================================


def run_pod(args) -> dict:
    import numpy as np

    import thunder_tpu.monitor as monitor
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.parallel import build_train_step, make_mesh
    from thunder_tpu.parallel.sharding import gpt_param_specs
    from thunder_tpu.parallel.train import opt_state_specs
    from thunder_tpu.resilience import chaos
    from thunder_tpu.resilience import federation as fed
    from thunder_tpu.resilience.autopilot import Autopilot
    from thunder_tpu.resilience.elastic import mesh_shape
    from thunder_tpu.resilience.preemption import CheckpointManager
    from thunder_tpu.resilience.snapshot import SnapshotStore

    import tempfile

    tmp = args.workdir or tempfile.mkdtemp(prefix="ttpu_pod_")
    log = os.path.join(tmp, "events.jsonl")
    monitor.set_event_log(log)

    plane = None
    if args.ops_plane:
        from thunder_tpu.observability import opsplane
        from thunder_tpu.observability.detect import DetectorConfig

        plane = opsplane.enable(
            port=0, serve=True,
            flightrec_dir=os.path.join(tmp, "flightrec"),
            detectors=DetectorConfig(
                min_samples=4, cooldown=8,
                spread_min_steps=3, spread_consecutive=2,
                # Compressed-timescale critpath band: the CPU-mesh base
                # step dwarfs the injected delay (and the 2-slice median
                # halves it), so the absolute straggler band sits low; re-
                # alerting every step (consecutive=1, cooldown=0) keeps the
                # band verdict the newest host-matched evidence when the
                # slice_loss decision lands (slice_spread's windowed means
                # also fire through the slow window, and the autopilot
                # cites newest-first).
                critpath_min_steps=4, critpath_straggler_frac=0.06,
                critpath_consecutive=1, critpath_cooldown=0,
            ),
        )
        _log(f"ops plane: http://127.0.0.1:{plane.port} "
             f"(/metrics /healthz /debug/state)")

    # ---- the federated workload -------------------------------------------
    devices_per_slice = args.devices // args.slices
    cfg = m.name_to_config(args.model)
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    rng = np.random.RandomState(args.seed)
    idx = rng.randint(0, cfg.vocab_size,
                      (args.batch, args.seq)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    def mesh_for_width(w):
        # Width w slices == a dp=w group of fsdp blocks: each emulated
        # slice owns one contiguous fsdp block of devices, and losing a
        # slice shrinks dp — the exact shrink the real federation performs.
        mesh = make_mesh(dp=w, fsdp=devices_per_slice)
        p_specs = gpt_param_specs(cfg, mesh)
        return mesh, (p_specs, opt_state_specs(p_specs))

    step_cache: dict = {}
    raw_step_cache: dict = {}

    def base_step_for(mesh):
        key = tuple(sorted((mesh_shape(mesh) or {}).items()))
        if key in step_cache:
            return step_cache[key]
        specs = gpt_param_specs(cfg, mesh)
        step, _ = build_train_step(
            cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=1e-2,
            executors=["jax"], donate=False,
        )
        raw_step_cache[key] = step  # the jittable the HLO auditor prices

        def step_fn(state):
            p, o = state
            p, o, loss = step(p, o, idx, tgt)
            return (p, o), float(np.asarray(loss))

        step_cache[key] = step_fn
        return step_fn

    accum_seen: list = []

    def build_for_width(mesh, width, accum):
        base = base_step_for(mesh)
        accum_seen.append(accum)
        if accum <= 1:
            return base

        # The loss-equivalent rescale made physical: the survivors run
        # `accum` micro-steps per driver step, so the degraded window's
        # measured tokens/s honestly drops with the width.
        def step_fn(state):
            loss = float("nan")
            for _ in range(accum):
                state, loss = base(state)
            return state, loss

        return step_fn

    full_mesh, _ = mesh_for_width(args.slices)
    specs0 = gpt_param_specs(cfg, full_mesh)
    _, opt0 = build_train_step(
        cfg, params, idx, tgt, mesh=full_mesh, param_specs=specs0, lr=1e-2,
        executors=["jax"], donate=False,
    )
    state0 = (params, opt0)
    tokens_per_step = args.batch * args.seq
    _log(f"workload: {args.model} B={args.batch} T={args.seq} "
         f"slices={args.slices} mesh={mesh_shape(full_mesh)}")

    # Warm the full-width step, then price the ideal step + resilience
    # overhead OUTSIDE the soak wall clock.
    full_step = base_step_for(full_mesh)
    state, _ = full_step(state0)
    ideal_step_s, overhead_pct, _ = _measure_pod_overheads(
        full_step, state, snapshot_every=args.snapshot_every)
    ideal_tps = tokens_per_step / ideal_step_s if ideal_step_s else 0.0
    _log(f"ideal step {ideal_step_s * 1e3:.1f}ms -> {ideal_tps:.0f} tok/s; "
         f"resilience overhead {overhead_pct:.2f}%")

    # ---- the fleet critical-path timeline (ISSUE 20) ----------------------
    # Per-slice clocks are EMULATED (one process = one real clock), so the
    # run injects known per-slice offsets and the skew estimator must
    # recover them from the lockstep-barrier rendezvous records — the
    # falsifiable half of the alignment story (critpath_skew_recovery_err_ms
    # in the committed round, gated by perf_report).
    from thunder_tpu.observability import timeline as tl_mod

    skew_rng = np.random.RandomState(args.seed * 7919 + 13)
    injected_skew = {
        sid: round(float(skew_rng.uniform(-0.4, 0.4)), 6)
        for sid in range(args.slices)
    }
    recorder = tl_mod.enable(
        bank=plane.bank if plane is not None else None,
        emulated_skew_s=injected_skew,
        host_label=lambda s: f"slice{s}",
    )
    # Wire classes come from the HLO auditor's static price of the full-
    # width step: the emulated fleet cannot measure per-leg wire time, so
    # the recorder charges exposed-ICI/DCN by the auditor's split — which
    # is exactly what keeps the ledger's static-vs-measured cross-check a
    # plumbing proof here (delta ~ 0) and a real disagreement on hardware.
    static_note = "unavailable"
    try:
        from thunder_tpu.analysis.hlo_audit import audit_jitted

        full_key = tuple(sorted((mesh_shape(full_mesh) or {}).items()))
        hrep = audit_jitted(raw_step_cache[full_key], params, opt0, idx, tgt)
        wire_us = hrep.exposed_us if hrep.exposed_us > 0 else sum(
            s.wire_us for s in hrep.sites)
        split = tl_mod.split_static_wire(hrep.sites, devices_per_slice)
        f_total = min(0.5, (wire_us * 1e-6) / ideal_step_s) \
            if ideal_step_s and wire_us > 0 else 0.0
        if f_total > 0:
            recorder.set_static_wire(
                f_total * split["ici_frac"], f_total * split["dcn_frac"],
                static_exposed_pct=100.0 * f_total,
            )
            static_note = (f"{len(hrep.sites)} site(s), exposed "
                           f"{100.0 * f_total:.2f}% of step "
                           f"(ici:dcn {split['ici_frac']:.2f}:"
                           f"{split['dcn_frac']:.2f})")
    except Exception as e:  # advisory: the soak must not die on pricing
        static_note = f"audit failed: {e}"
    if recorder.static_exposed_pct is None:
        # Datasheet placeholder so the wire classes stay observable even
        # when the auditor finds nothing to price.
        recorder.set_static_wire(0.03, 0.01, static_exposed_pct=4.0)
    _log(f"critpath timeline armed: injected skew "
         f"{ {f'slice{k}': v for k, v in injected_skew.items()} }; "
         f"static wire {static_note}")

    # ---- the controller + cross-slice snapshot ring -----------------------
    ledger = fed.FederationLedger(args.slices)
    autopilot = Autopilot()
    controller = fed.FleetController(
        ledger, autopilot,
        rejoin_backoff_s=args.rejoin_backoff_s,
        hysteresis_s=args.rejoin_backoff_s,
    )
    stores = [SnapshotStore(host=i, ring=args.snapshot_ring)
              for i in range(args.slices)]
    SnapshotStore.make_ring(stores)
    mgr = CheckpointManager(os.path.join(tmp, "ckpt"), keep=3,
                            backoff_s=0.01, store=stores[0])

    spec = make_spec(args)
    _log(f"schedule (seed={args.seed}): {spec}")

    # Per-width wall-time buckets for the honest degraded-goodput split.
    t_last = [time.perf_counter()]
    width_wall: dict = {}
    width_steps: dict = {}
    min_width = [args.slices]

    def on_step(step, loss, width):
        now = time.perf_counter()
        width_wall[width] = width_wall.get(width, 0.0) + (now - t_last[0])
        width_steps[width] = width_steps.get(width, 0) + 1
        t_last[0] = now
        min_width[0] = min(min_width[0], width)

    slice_feed = plane.bank.note_slice_step if (
        plane is not None and plane.bank is not None) else None

    wall0 = time.perf_counter()
    t_last[0] = wall0
    halted = None
    with chaos.chaos_scope(spec):
        try:
            state, report = fed.run_federated_training(
                controller, build_for_width, state0, args.steps,
                manager=mgr, mesh_for_width=mesh_for_width, stores=stores,
                snapshot_every=args.snapshot_every,
                recover_after=args.recover_after, on_step=on_step,
                slice_step_time=slice_feed, timeline=recorder,
            )
        except fed.AutopilotHalt as e:
            halted = str(e)
            report = getattr(e, "report", None) or fed.FleetReport(
                losses=[], full_width=args.slices, final_width=0)
    wall_s = time.perf_counter() - wall0
    mgr.close()

    ops_healthz = None
    ops_federation = None
    ops_port = plane.port if plane is not None else None
    if plane is not None:
        try:
            import urllib.error
            import urllib.request

            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{plane.port}/healthz",
                        timeout=5) as r:
                    body = r.read().decode()
            except urllib.error.HTTPError as e:
                body = e.read().decode()
            ops_healthz = json.loads(body).get("status")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.port}/debug/state",
                    timeout=5) as r:
                dbg = json.loads(r.read().decode())
            fed_dbg = dbg.get("federation") or {}
            ops_federation = {"width": fed_dbg.get("width"),
                              "n_slices": fed_dbg.get("n_slices")}
        except Exception as e:
            ops_healthz = f"unreachable: {e}"
    fed.install_ledger(None)

    monitor.set_event_log(None)
    summary, diags = replay_events(log, storm_threshold=64)
    errors = [d for d in diags if d.severity >= Severity.ERROR]
    for line in format_replay(summary, diags).splitlines():
        _log(line)

    # ---- ledger-derived invariants ----------------------------------------
    recs = []
    with open(log) as f:
        for line in f:
            try:
                recs.append(json.loads(line))
            except ValueError:
                continue
    restores = [r for r in recs if r.get("kind") == "restore" and r.get("ok")]
    # Each slice-loss episode's recovery restore: the first ok restore
    # after the fault_injected record. Must be the buddy's peer-RAM tier.
    loss_tiers = []
    shrink_latencies = []
    for i, r in enumerate(recs):
        if r.get("kind") == "fault_injected" and r.get("seam") in (
                "slice_loss", "slice_flap"):
            nxt = next((q for q in recs[i + 1:]
                        if q.get("kind") == "restore" and q.get("ok")), None)
            if nxt is not None:
                loss_tiers.append(nxt["tier"])
                shrink_latencies.append(float(nxt["ts"]) - float(r["ts"]))
    disk_after_anchor = sum(
        1 for r in restores[1:] if r.get("tier") == "disk")
    flap_refailures = sum(
        1 for r in recs if r.get("kind") == "slice_state"
        and r.get("from") == "cooldown" and r.get("to") == "lost")
    # Regrow-to-full-width latency per episode: lost slice_state ->
    # the regrow decision's elastic_resume back at full width.
    regrow_s = 0.0
    lost_ts = None
    for r in recs:
        if (r.get("kind") == "slice_state" and r.get("to") == "lost"
                and lost_ts is None):
            lost_ts = float(r["ts"])
        if (r.get("kind") == "autopilot_decision"
                and r.get("actuator") == "regrow_dp" and lost_ts is not None):
            regrow_s = max(regrow_s, float(r["ts"]) - lost_ts)
            lost_ts = None
    anomalies = dict(summary.get("anomalies") or {})

    # ---- the committed critical-path round (CRITPATH_r*.json) -------------
    # Read the recorder BEFORE tearing it down: EWMA class fractions, the
    # recovered per-slice skew (checked against what this run injected),
    # the static-vs-measured cross-check, and the detector/autopilot joins
    # proven from the replayed ledger.
    ledger_snap = recorder.ledger.snapshot()
    skew_est = recorder.skew_estimates()
    crosscheck = recorder.crosscheck()
    fracs = recorder.ledger.fractions()
    strag_hosts = ledger_snap.get("straggler_hosts") or {}
    strag_host = (max(strag_hosts, key=strag_hosts.get)
                  if strag_hosts else None)
    strag_label = None if strag_host is None else f"slice{strag_host}"
    # Injected offsets re-centered to the fleet-median clock — the frame
    # the estimator reports in (absolute clock is unobservable from
    # rendezvous records alone).
    inj = {s: injected_skew.get(s, 0.0) for s in skew_est}
    inj_sorted = sorted(inj.values())
    inj_med = (0.0 if not inj_sorted else
               (inj_sorted[(len(inj_sorted) - 1) // 2]
                + inj_sorted[len(inj_sorted) // 2]) / 2.0)
    inj_centered = {s: v - inj_med for s, v in inj.items()}
    recovery_err_ms = max(
        (abs(e.offset_s - inj_centered[s]) * 1e3
         for s, e in skew_est.items()), default=float("nan"))
    conf = [e.confidence for e in skew_est.values() if not e.outlier]
    cited = sum(
        1 for r in recs
        if r.get("kind") == "autopilot_decision"
        and isinstance(r.get("evidence"), dict)
        and isinstance(r["evidence"].get("anomaly"), dict)
        and r["evidence"]["anomaly"].get("anomaly") == "bottleneck_shift")
    critpath = {
        "metric": "critpath_exposed_pct",
        "value": crosscheck.get("measured_exposed_pct"),
        "unit": "%",
        "seed": args.seed,
        "n_devices": args.devices,
        "n_slices": args.slices,
        "model": args.model,
        "steps": args.steps,
        "critpath_steps": ledger_snap.get("steps"),
        "critpath_nonzero_classes": sum(
            1 for v in (ledger_snap.get("totals_s") or {}).values() if v > 0),
        "critpath_frac_sum": round(sum(fracs.values()), 4),
        "critpath_dominant": recorder.ledger.dominant(),
        # The straggler-wait attribution: the seeded slow slice must own
        # the straggler-credited steps.
        "critpath_straggler_host": strag_label,
        "critpath_expected_slow_host": "slice1",
        "critpath_straggler_host_match": int(strag_label == "slice1"),
        # Clock alignment, falsified against the injected offsets.
        "critpath_skew": {f"slice{s}": e.as_dict()
                          for s, e in sorted(skew_est.items())},
        "critpath_skew_injected_ms": {
            f"slice{s}": round(v * 1e3, 3)
            for s, v in sorted(inj_centered.items())},
        "critpath_skew_recovery_err_ms": round(recovery_err_ms, 3),
        "critpath_skew_min_confidence": round(min(conf), 4) if conf else 0.0,
        "critpath_skew_outlier_hosts": sum(
            1 for e in skew_est.values() if e.outlier),
        # Static-vs-measured exposed-collective cross-check (the
        # disagreement is itself a surfaced number).
        "critpath_measured_exposed_pct":
            crosscheck.get("measured_exposed_pct"),
        "critpath_static_exposed_pct": crosscheck.get("static_exposed_pct"),
        "critpath_delta_static_pct": crosscheck.get("delta_static_pct"),
        # Detector + autopilot joins from the replayed ledger.
        "critpath_bottleneck_shift_anomalies": int(
            anomalies.get("bottleneck_shift") or 0),
        "critpath_cited_decisions": cited,
        "critpath_per_step": list(ledger_snap.get("last_steps") or []),
        "events_log": log,
    }
    for c, f in fracs.items():
        critpath[f"critpath_{c}_frac"] = round(f, 4)
    if getattr(args, "critpath_out", None):
        with open(args.critpath_out, "w") as f:
            f.write(json.dumps(critpath) + "\n")
        _log(f"critpath round -> {args.critpath_out}")
    _log("critpath: " + json.dumps(
        {k: critpath[k] for k in (
            "critpath_steps", "critpath_nonzero_classes",
            "critpath_dominant", "critpath_straggler_host",
            "critpath_skew_recovery_err_ms",
            "critpath_bottleneck_shift_anomalies",
            "critpath_cited_decisions")}))
    tl_mod.disable()

    if plane is not None:
        from thunder_tpu.observability import opsplane

        opsplane.disable()

    useful_tokens = args.steps * tokens_per_step
    tps = useful_tokens / wall_s if wall_s else 0.0
    goodput = tps * (1.0 - overhead_pct / 100.0)
    ratio = goodput / ideal_tps if ideal_tps else 0.0
    degraded_wall = sum(s for w, s in width_wall.items() if w < args.slices)
    degraded_steps = sum(n for w, n in width_steps.items() if w < args.slices)
    degraded_tps = (degraded_steps * tokens_per_step / degraded_wall
                    if degraded_wall else 0.0)

    result = {
        "metric": "soak_pod_goodput",
        "value": round(goodput, 1),
        "unit": "tokens/s",
        "seed": args.seed,
        "n_devices": args.devices,
        "n_slices": args.slices,
        "mesh": mesh_shape(full_mesh),
        "model": args.model,
        "batch": args.batch,
        "seq": args.seq,
        "steps": args.steps,
        "soak_pod_goodput_tokens_per_sec": round(goodput, 1),
        "soak_pod_tokens_per_sec": round(tps, 1),
        "soak_pod_ideal_tokens_per_sec": round(ideal_tps, 1),
        "soak_pod_goodput_ratio": round(ratio, 4),
        "resilience_overhead_pct": round(overhead_pct, 2),
        "soak_pod_wall_s": round(wall_s, 2),
        # Degraded-mode honesty: tokens/s measured INSIDE the reduced-width
        # window, with the accum-rescale micro-steps charged to it.
        "soak_pod_degraded_steps": degraded_steps,
        "soak_pod_degraded_tokens_per_sec": round(degraded_tps, 1),
        "soak_pod_grad_accum_max": max(accum_seen) if accum_seen else 1,
        "soak_pod_partitioned_steps": report.partitioned_steps,
        # Fleet trajectory: shrank, trained degraded, regrew to full width,
        # in ONE process.
        "soak_pod_full_width": report.full_width,
        "soak_pod_final_width": report.final_width,
        "soak_pod_min_width": min_width[0],
        "soak_pod_shrinks": report.shrinks,
        "soak_pod_regrows": report.regrows,
        "soak_pod_flap_refailures": flap_refailures,
        # Which optional seams this run's schedule carried, so the perf
        # gate knows which absolute invariants apply (smoke runs inject
        # only the slice loss).
        "soak_pod_flap_injected": int(not args.smoke),
        "soak_pod_slow_injected": int(not args.smoke),
        "soak_pod_restarts": 0 if halted is None else 1,
        "soak_pod_halted": halted,
        "soak_pod_steps_executed": report.steps_executed,
        "soak_pod_final_loss": next(
            (v for v in reversed(report.losses) if v is not None), None),
        # The tier proof: every slice-loss recovery read the cross-slice
        # buddy's RAM; disk served only the step-0 anchor.
        "soak_pod_slice_loss_restores": len(loss_tiers),
        "soak_pod_slice_loss_restore_tiers": loss_tiers,
        # Numeric form of the tier proof for the perf gate (which keeps
        # only numeric fields): restores that did NOT come from peer RAM.
        "soak_pod_slice_loss_nonpeer_restores": sum(
            1 for t in loss_tiers if t != "peer"),
        "soak_pod_disk_restores_after_anchor": disk_after_anchor,
        "soak_pod_restore_tiers": summary.get("restore_tiers") or {},
        "soak_pod_shrink_latency_s": round(max(shrink_latencies), 3)
        if shrink_latencies else 0.0,
        "soak_pod_regrow_to_full_s": round(regrow_s, 3),
        "soak_pod_faults_injected": len(summary.get("faults_injected") or []),
        "soak_pod_decisions": summary.get("autopilot_decisions") or {},
        "soak_pod_unrecovered": len(summary.get("unrecovered_faults") or []),
        "soak_pod_unactuated": len(summary.get("unactuated_decisions") or []),
        "soak_pod_replay_errors": len(errors),
        # Ops plane: the DCN-tier spread detector's verdicts + the
        # federation rollup served over HTTP during the run.
        "soak_pod_anomalies": anomalies,
        "soak_pod_slice_spread_anomalies": int(
            anomalies.get("slice_spread") or 0),
        "soak_pod_bottleneck_shift_anomalies": int(
            anomalies.get("bottleneck_shift") or 0),
        "soak_pod_ops_port": ops_port,
        "soak_pod_ops_healthz": ops_healthz,
        "soak_pod_ops_federation": ops_federation,
        "events_log": log,
    }
    _log(f"goodput {goodput:.0f} tok/s ({ratio * 100:.1f}% of ideal "
         f"{ideal_tps:.0f}) over {wall_s:.1f}s wall; degraded window "
         f"{degraded_steps} step(s) at {degraded_tps:.0f} tok/s; "
         f"{report.shrinks} shrink(s), {report.regrows} regrow(s), "
         f"{flap_refailures} flap re-failure(s), "
         f"unrecovered={result['soak_pod_unrecovered']}, "
         f"unactuated={result['soak_pod_unactuated']}")
    _log(f"tiers: slice-loss restores {loss_tiers or 'none'}, "
         f"{disk_after_anchor} disk restore(s) after the anchor; "
         f"slice_spread anomalies {result['soak_pod_slice_spread_anomalies']}")
    return result


# =============================================================================
# Driver
# =============================================================================


def pod_ok(result: dict) -> bool:
    """The pod soak's pass condition (the ISSUE 18 acceptance gate)."""
    loss = result.get("soak_pod_final_loss")
    ok = (
        result.get("soak_pod_unrecovered") == 0
        and result.get("soak_pod_unactuated") == 0
        and result.get("soak_pod_replay_errors") == 0
        and result.get("soak_pod_restarts") == 0
        and loss is not None and loss == loss  # not NaN
        # Training continued through the loss and regrew to full DP width.
        and result.get("soak_pod_degraded_steps", 0) > 0
        and result.get("soak_pod_min_width", 0)
        < result.get("soak_pod_full_width", 0)
        and result.get("soak_pod_final_width")
        == result.get("soak_pod_full_width")
        and result.get("soak_pod_shrinks", 0)
        == result.get("soak_pod_regrows", -1) > 0
        # Every slice-loss recovery came from the buddy's peer RAM.
        and result.get("soak_pod_slice_loss_restores", 0) > 0
        and all(t == "peer"
                for t in result.get("soak_pod_slice_loss_restore_tiers", ()))
        and result.get("soak_pod_disk_restores_after_anchor") == 0
    )
    if ok and result.get("soak_pod_flap_refailures", 0) > 0:
        # The flap episode must not have bought extra shrinks: episodes
        # (loss + flap) == 2 decisions each way, never 3.
        ok = result.get("soak_pod_shrinks") == result.get("soak_pod_regrows")
    if ok and result.get("soak_pod_ops_port") is not None \
            and result.get("soak_pod_anomalies", {}).get("slice_spread") is not None:
        ok = result.get("soak_pod_ops_healthz") not in (None, "")
    return ok


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="soak_pod.py",
        description="Slice-failure soak on the federated virtual mesh "
                    "(SOAK_POD series)",
    )
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--slices", type=int, default=2)
    p.add_argument("--model", default="gpt-tiny")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=32)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--snapshot-every", type=int, default=2)
    p.add_argument("--snapshot-ring", type=int, default=4)
    p.add_argument("--recover-after", type=int, default=6,
                   help="steps after a slice_loss before the victim "
                        "reports healthy (the scheduler re-grant stand-in)")
    p.add_argument("--rejoin-backoff-s", type=float, default=0.05,
                   help="controller rejoin backoff == hysteresis window, "
                        "sized to the CPU mesh's compressed timescale")
    p.add_argument("--slow-delay-s", type=float, default=0.05,
                   help="per-step inflation of the slice_slow window")
    p.add_argument("--ops-plane", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--smoke", action="store_true",
                   help="CI-sized run: 2 slices x 2 devices, 16 steps, one "
                        "scripted slice loss (lint_traces --federation)")
    p.add_argument("--workdir", default=None)
    p.add_argument("--out", default=None, help="also write the JSON here")
    p.add_argument("--critpath-out", default=None,
                   help="write the fleet critical-path round here "
                        "(the committed CRITPATH_r*.json series)")
    p.add_argument("--_subprocess", action="store_true",
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.smoke:
        args.devices, args.steps = 4, 16
        args.recover_after = 4
    if args.devices % args.slices:
        p.error("--devices must divide evenly into --slices")

    import jax

    if len(jax.devices()) < args.devices and not args._subprocess:
        import subprocess

        env = {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS":
                f"--xla_force_host_platform_device_count={args.devices}",
            "THUNDER_TPU_RETRY_BACKOFF_S": "0",
        }
        cmd = [sys.executable, os.path.abspath(__file__), "--_subprocess"] + [
            a for a in (argv if argv is not None else sys.argv[1:])
        ]
        r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=3000)
        sys.stderr.write(r.stderr[-8000:] if len(r.stderr) > 8000
                         else r.stderr)
        if r.returncode != 0:
            print(f"soak_pod subprocess failed:\n{r.stdout[-2000:]}",
                  file=sys.stderr)
            return r.returncode
        line = r.stdout.strip().splitlines()[-1]
        json.loads(line)  # malformed output must fail loudly
        print(line)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        return 0

    os.environ.setdefault("THUNDER_TPU_RETRY_BACKOFF_S", "0")
    result = run_pod(args)
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if pod_ok(result) else 1


if __name__ == "__main__":
    raise SystemExit(main())
