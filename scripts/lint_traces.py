#!/usr/bin/env python
"""Run the static trace verifier over the repo's example programs.

CI/tooling entry point for the analysis/ framework (see
docs/trace_invariants.md): every program below is traced, pushed through the
default pass pipeline (acquisition → DCE → CSE → claiming → del_last_used)
with `examine.lint`, and — for the gradient workloads — compiled end-to-end
under THUNDER_TPU_CHECKS=1 so each transform pass (autodiff joint rewrite,
autocast, RNG functionalization) is verified at the point it runs.

Exit status is non-zero if any ERROR-severity diagnostic is found.

The full run also executes the bench regression gate
(``scripts/perf_report.py --history --gate``) over the committed
``BENCH_r*.json`` rounds, so a future bench regression beyond threshold
fails this script loudly (known regressions are acknowledged in
``BENCH_ACK.json``).

Usage:
    python scripts/lint_traces.py            # all programs + bench gate
    python scripts/lint_traces.py gpt        # substring-filter by name
    python scripts/lint_traces.py --events LOG.jsonl [LOG2.jsonl ...]
        # replay observability event log(s) (THUNDER_TPU_EVENTS /
        # jit(events=...)): validates the JSONL schema and flags recompile
        # storms; several per-host logs are merged with stable ordering
        # (thunder_tpu.analysis.events; docs/observability.md)
    python scripts/lint_traces.py --static
        # static planner smoke (ISSUE 10; docs/trace_invariants.md): GPT
        # fwd and fwd+bwd predicted peak HBM within 15% of the
        # instrument="memory" measured high-water; fsdp4·tp2 collective
        # schedule certifies and uncertified reorders + donation/alias
        # hazards are flagged; the de-opt ladder under the chaos oom@<3
        # memory ceiling reaches its fitting level with strictly fewer
        # failed XLA compiles than blind climbing
    python scripts/lint_traces.py --schedule
        # comm-scheduler smoke (ISSUE 13; docs/performance.md "collective
        # overlap"): the fsdp4·tp2 grad trace schedules with hidden wire
        # > 0 for the top fsdp synchronize and a grad reduce_scatter,
        # re-certifies with the identical per-axis order, backs its hoists
        # off under a capacity squeeze instead of predicting an OOM, and
        # a chaos-corrupted placement (sched_bad) or compile failure
        # demotes cleanly to the unscheduled order / L1
    python scripts/lint_traces.py --chaos
        # resilience smoke (docs/robustness.md): run the GPT gradient
        # pipeline under a canned fault schedule (kernel raise, compile
        # failure, OOM, NaN poison) and fail on any unrecovered fault,
        # non-baseline-equal recovery, or missing degradation event in the
        # JSONL log (replayed through the correlation rule)
    python scripts/lint_traces.py --soak
        # fleet-autopilot soak smoke (ISSUE 11; docs/robustness.md "fleet
        # autopilot"): a short deterministic (seeded) scripts/soak_fleet.py
        # run on the 8-device virtual mesh — must end with zero unrecovered
        # faults and zero unactuated autopilot decisions, exercise at least
        # one decision of every policy class (elastic_resume,
        # quarantine_rerun, deopt_escalate, checkpoint_halt), and land a
        # per-fault recovery cost within the soak noise floor of the
        # committed SOAK_r*.json round; full runs gate the committed
        # series via perf_report --gate
    python scripts/lint_traces.py --ops
        # live ops-plane smoke (ISSUE 15; docs/observability.md "ops
        # plane"): start the per-host HTTP endpoint against a chaos'd GPT
        # step — /healthz must flip degraded on a seeded straggler stream,
        # /metrics must scrape mid-run with host labels AND the
        # always-export drop counter at 0, an injected hang must leave a
        # schema-valid flight-recorder dump, and the measured ops-plane
        # overhead must stay under 1% of the step time (the same
        # composition bench.py records as ops_overhead_pct)
    python scripts/lint_traces.py --hlo
        # HLO-auditor smoke (ISSUE 16; docs/trace_invariants.md "HLO
        # auditor"): the fsdp4·tp2 build_train_step executable's compiled
        # HLO must yield ≥1 partitioner-inserted collective of every
        # family the partitioner emits (all-gather, all-reduce, derived
        # reduce-scatter, collective-permute) with nonzero wire bytes, a
        # schema-valid report JSON, analyze cost <5% of the XLA compile,
        # and garbage HLO must degrade to a sharp_edge advisory without
        # breaking the compile
    python scripts/lint_traces.py --chaos-multihost
        # mesh-wide resilience smoke (ISSUE 9): the FSDP×TP training step
        # on a virtual 8-device mesh under a canned host-loss +
        # collective-hang + SDC schedule — collective hang must raise the
        # typed watchdog timeout naming trace line + suspected host,
        # host loss must checkpoint and elastically resume on the shrunk
        # fsdp2·tp2 mesh reproducing the uninterrupted loss trajectory,
        # SDC must be caught by the replica-checksum guard and re-run;
        # every fault_injected needs its paired recovery event
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _programs():
    """(name, fn, args) — the example-program corpus: the ops exercised by
    examples/train.py's training step plus representative small programs."""
    import thunder_tpu.torch as ttorch
    from thunder_tpu.models import gpt as m
    from thunder_tpu.core import dtypes

    rng = np.random.RandomState(0)
    x44 = rng.randn(4, 4).astype(np.float32)
    x48 = rng.randn(4, 8).astype(np.float32)
    w86 = rng.randn(6, 8).astype(np.float32)

    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    return [
        ("elementwise-chain", lambda a: ((a * 2.0).tanh() + a).sum(), (x44,)),
        ("linear-gelu", lambda a, w: ttorch.sum(ttorch.gelu(ttorch.linear(a, w))), (x48, w86)),
        ("reduction-mix", lambda a: (a.sum(0) * a.mean()).sum(), (x44,)),
        ("dropout-rng", lambda a: ttorch.dropout(a, p=0.5, training=True).sum(), (x44,)),
        ("inplace-functionalized", _inplace_prog, (x44,)),
        ("gpt-tiny-forward", lambda p, i: m.forward(p, i, cfg), (params, idx)),
        ("gpt-tiny-loss", lambda p, i, t: m.loss_fn(p, i, t, cfg), (params, idx, tgt)),
    ]


def _inplace_prog(a):
    import thunder_tpu.torch as ttorch

    b = ttorch.abs(a)
    b += 1.0
    return ttorch.sum(b)


def _grad_workloads():
    """(name, staged callable, args) compiled with the verifier scoped on —
    exercises the grad/autocast/RNG transform passes the pipeline-level lint
    stages don't reach."""
    import thunder_tpu as ttpu
    from thunder_tpu.models import gpt as m
    from thunder_tpu.core import dtypes

    rng = np.random.RandomState(0)
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    loss = lambda p, i, t: m.loss_fn(p, i, t, cfg)  # noqa: E731

    return [
        ("gpt-tiny-backward", ttpu.value_and_grad(loss, executors=["jax"], debug_checks=True),
         (params, idx, tgt)),
        ("gpt-tiny-backward-autocast",
         ttpu.value_and_grad(loss, executors=["jax"], debug_checks=True, autocast="bfloat16"),
         (params, idx, tgt)),
    ]


def _replay(paths: list, storm_threshold: int) -> int:
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events

    # One path keeps single-log semantics (per-line diagnostics); several are
    # merged with stable (ts, host, pid, seq) ordering before replay.
    source = paths[0] if len(paths) == 1 else paths
    summary, diags = replay_events(source, storm_threshold=storm_threshold)
    print(format_replay(summary, diags))
    n_errors = sum(1 for d in diags if d.severity >= Severity.ERROR)
    print(f"\nlint_traces --events: {n_errors} error(s), "
          f"{sum(1 for d in diags if d.severity == Severity.WARNING)} warning(s)")
    return 1 if n_errors else 0


def _bench_history_gate(glob_pat: str = "BENCH_r*.json",
                        min_rounds: int = 2) -> int:
    """Run the bench regression gate over one committed bench series
    (``BENCH_r*.json`` single-host, ``MULTICHIP_BENCH_r*.json`` multichip —
    scripts/perf_report.py). Returns the number of errors (0 when fewer
    than ``min_rounds`` committed rounds exist; the SOAK_POD series passes
    ``min_rounds=1`` because its absolute federation invariants gate from
    the first committed round)."""
    import glob

    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(scripts_dir)
    paths = sorted(glob.glob(os.path.join(repo_root, glob_pat)))
    if len(paths) < min_rounds:
        return 0
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    from perf_report import run_history_gate

    print(f"--- bench regression gate (perf_report --history --gate) [{glob_pat}]")
    return run_history_gate(paths, gate=True)


# The committed MULTICHIP_BENCH schema: what every round must carry for the
# series to stay comparable (scripts/bench_multichip.py emits these; the
# --multichip smoke and docs/performance.md "distributed telemetry" assert
# them).
_MULTICHIP_REQUIRED_KEYS = (
    "metric", "value", "unit", "n_devices", "mesh", "model", "batch", "seq",
    "train_iter_s", "train_iter_synced_s", "train_iter_strict_sync_s",
    "train_tokens_per_sec", "train_mfu", "device_spec", "train_flops_per_step",
    "multichip_trace_claim_s", "multichip_xla_compile_s", "compile_phases",
)


def _multichip_smoke() -> int:
    """--multichip: the distributed-observatory smoke (ISSUE 8 satellite).
    Runs a reduced-iteration ``scripts/bench_multichip.py`` on an 8-device
    virtual CPU mesh, asserts the bench JSON schema (every key the committed
    ``MULTICHIP_BENCH_r*.json`` series gates on), asserts collective rows are
    present in the profiled attribution with the hidden/exposed overlap
    split, and runs ``perf_report.py --gate`` over the committed multichip
    series. Returns the error count."""
    import json
    import subprocess
    import tempfile

    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(tempfile.mkdtemp(prefix="ttpu_mc_smoke_"), "mc.json")
    cmd = [sys.executable, os.path.join(scripts_dir, "bench_multichip.py"),
           "--devices", "8", "--iters", "3", "--profile-steps", "2",
           "--out", out_path]
    print("--- multichip smoke: " + " ".join(cmd))
    n_errors = 0
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1200)
    tail = r.stderr.strip().splitlines()[-12:]
    for line in tail:
        print(f"    {line}")
    if r.returncode != 0:
        print(f"    FAILED: bench_multichip exited {r.returncode}")
        return 1
    with open(out_path) as f:
        result = json.load(f)

    missing = [k for k in _MULTICHIP_REQUIRED_KEYS if k not in result]
    if missing:
        n_errors += 1
        print(f"    FAILED: bench JSON missing keys: {missing}")
    else:
        print(f"    schema OK ({len(_MULTICHIP_REQUIRED_KEYS)} required keys)")

    # Collective attribution: the profiled run must classify wire ops into
    # per-family rows carrying the hidden/exposed split.
    colls = result.get("collectives") or {}
    bad = [c for c, v in colls.items()
           if not all(k in v for k in
                      ("us_per_step", "hidden_us_per_step",
                       "exposed_us_per_step", "calls"))]
    if not colls:
        n_errors += 1
        print("    FAILED: no collective rows in the profiled attribution "
              "(expected all-gather/all-reduce/... on the FSDP×TP step)")
    elif bad:
        n_errors += 1
        print(f"    FAILED: collective rows missing overlap fields: {bad}")
    else:
        print(f"    collective rows OK: {sorted(colls)} "
              f"({result.get('spmd_collective_exposed_pct')}% of device time "
              "exposed, SPMD lanes)")

    # The explicit-collective overlap workload (scheduler + static×measured
    # join) is diagnostic: its absence is recorded, not fatal, but a
    # recorded failure in the smoke IS an error — the seam must work in CI.
    if result.get("overlap_error"):
        n_errors += 1
        print(f"    FAILED: overlap workload errored: {result['overlap_error']}")
    elif result.get("overlap"):
        shown = result.get("overlap_sites_shown")
        total = result.get("overlap_sites_total")
        moves = (result.get("comm_schedule") or {}).get("moves", 0)
        exp = result.get("collective_exposed_pct")
        exp_raw = result.get("collective_exposed_pct_unscheduled")
        if total is None or shown is None:
            n_errors += 1
            print("    FAILED: overlap table lacks the no-silent-caps "
                  "site counts (overlap_sites_total/shown)")
        elif moves < 1 or exp is None or exp_raw is None or exp >= exp_raw:
            n_errors += 1
            print(f"    FAILED: scheduler must move sites and cut the static "
                  f"exposed pct (moves={moves}, {exp_raw} -> {exp})")
        else:
            print(f"    overlap table OK: {shown}/{total} site(s), "
                  f"{moves} scheduler move(s), static exposed "
                  f"{exp_raw}% -> {exp}%")

    n_errors += _bench_history_gate("MULTICHIP_BENCH_r*.json")
    print(f"\nlint_traces --multichip: {n_errors} error(s)")
    return n_errors


def _hlo_smoke() -> int:
    """--hlo: re-exec this script on a virtual 8-device CPU mesh (the
    device-count flag must be set before jax initializes) and run
    :func:`_hlo_inner` there. Returns the error count."""
    import subprocess

    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "THUNDER_TPU_RETRY_BACKOFF_S": "0",
    }
    cmd = [sys.executable, os.path.abspath(__file__), "--_hlo-inner"]
    print("--- hlo smoke (subprocess, 8 virtual devices)")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=1200)
    out = (r.stdout + r.stderr).strip().splitlines()
    for line in out[-40:]:
        print(f"    {line}")
    if r.returncode != 0:
        print(f"    FAILED: inner smoke exited {r.returncode}")
        return 1
    return 0


# Every key one committed HloScheduleReport.to_json() must carry for the
# static series (bench r05+, docs/performance.md "static HLO audit") to stay
# comparable.
_HLO_REPORT_REQUIRED_KEYS = (
    "v", "module", "device", "n_ops", "n_computations", "collectives",
    "inserted_collectives", "explicit_collectives", "fusions", "layout_copies",
    "host_transfers", "flops", "hbm_bytes", "comm_bytes", "compute_us",
    "wire_us", "hidden_us", "exposed_us", "exposed_pct", "sites",
)
_HLO_SITE_REQUIRED_KEYS = (
    "name", "opcode", "family", "computation", "group_size", "wire_bytes",
    "wire_us", "hidden_us", "exposed_us", "inserted", "derived",
)


def _hlo_inner() -> int:
    """The HLO-auditor smoke (ISSUE 16 acceptance), run with 8 virtual
    devices: the fsdp4·tp2 ``build_train_step`` executable's compiled HLO
    must yield ≥1 partitioner-inserted collective of every family the
    partitioner emits on this step (all-gather, all-reduce, reduce-scatter
    — CPU XLA spells it as all-reduce+shard-slice, recovered as derived —
    and collective-permute), each with nonzero wire bytes; the report's
    ``to_json()`` must be schema-valid; the analyze pass must cost <5% of
    the XLA compile it piggybacks on; garbage HLO must raise ``ValueError``
    from ``audit_hlo`` and, through the compile-phase path, degrade to a
    ``sharp_edge`` advisory with the compile unharmed."""
    import json
    import tempfile
    import time

    import numpy as np

    import thunder_tpu as ttpu
    from thunder_tpu.analysis import hlo_audit
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.parallel import build_train_step, make_mesh
    from thunder_tpu.parallel.sharding import gpt_param_specs

    n_errors = 0
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    rng = np.random.RandomState(0)
    idx = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    print("--- hlo smoke: audit the fsdp4-tp2 build_train_step executable")
    mesh = make_mesh(fsdp=4, tp=2)
    step, opt0 = build_train_step(
        cfg, params, idx, tgt, mesh=mesh, param_specs=gpt_param_specs(cfg, mesh),
        lr=1e-2, executors=["jax"], donate=False,
    )
    t0 = time.perf_counter()
    text = step.lower(params, opt0, idx, tgt).compile().as_text()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    rep = hlo_audit.audit_hlo(text)
    analyze_s = time.perf_counter() - t0

    # Family coverage: the ISSUE 16 acceptance families, each inserted by
    # the partitioner (not explicit dist_prims) and carrying wire bytes.
    expected = ("all-gather", "all-reduce", "reduce-scatter", "collective-permute")
    bad = [f for f in expected
           if not ((agg := rep.by_family.get(f))
                   and agg["count"] >= 1 and agg["wire_bytes"] > 0
                   and agg["inserted"] >= 1)]
    if bad:
        n_errors += 1
        print(f"    FAILED: families missing/uninserted/zero-wire: {bad} "
              f"(got {sorted(rep.by_family)})")
    else:
        derived_rs = sum(1 for s in rep.sites if s.family == "reduce-scatter"
                         and s.derived)
        print("    families OK: " + ", ".join(
            f"{f}×{rep.by_family[f]['count']}" for f in expected)
            + f" ({rep.inserted_collectives} inserted, {derived_rs} derived "
            f"reduce-scatter), static exposed {rep.exposed_pct:.1f}%")

    js = rep.to_json()
    missing = [k for k in _HLO_REPORT_REQUIRED_KEYS if k not in js]
    site_missing = [k for k in _HLO_SITE_REQUIRED_KEYS
                    for s in js["sites"][:1] if k not in s]
    json.dumps(js)  # must be JSON-serializable end to end
    if missing or site_missing or not js["sites"]:
        n_errors += 1
        print(f"    FAILED: report schema (missing={missing}, "
              f"site_missing={site_missing}, sites={len(js['sites'])})")
    else:
        print(f"    schema OK: {len(_HLO_REPORT_REQUIRED_KEYS)} report keys, "
              f"{len(js['sites'])} sites serialized")

    if analyze_s >= 0.05 * compile_s:
        n_errors += 1
        print(f"    FAILED: analyze {analyze_s * 1e3:.0f}ms >= 5% of the "
              f"{compile_s:.2f}s XLA compile it piggybacks on")
    else:
        print(f"    overhead OK: analyze {analyze_s * 1e3:.0f}ms = "
              f"{analyze_s / compile_s * 100:.1f}% of the {compile_s:.2f}s "
              f"XLA compile (< 5%)")

    print("--- hlo smoke: garbage HLO degrades to a sharp_edge advisory")
    try:
        hlo_audit.audit_hlo("this is not an HLO module at all")
        n_errors += 1
        print("    FAILED: audit_hlo accepted garbage without a ValueError")
    except ValueError:
        pass

    # The compile-phase path: seed the same failure INSIDE the auditor the
    # api.py phase calls; the compile must succeed, the result must be
    # right, and the event log must carry the advisory sharp_edge.
    log = os.path.join(tempfile.mkdtemp(prefix="ttpu_hlo_"), "events.jsonl")
    real_parse = hlo_audit.parse_hlo_module
    hlo_audit.parse_hlo_module = lambda text: real_parse("seeded garbage")
    try:
        jf = ttpu.jit(lambda a: (a * 2.0).sum(), executors=["jax"], events=log)
        out = float(np.asarray(jf(np.ones((4, 4), np.float32))))
    except Exception as e:  # noqa: BLE001 — an escaped auditor error IS the failure
        n_errors += 1
        out = None
        print(f"    FAILED: corrupted auditor broke the compile: "
              f"{type(e).__name__}: {e}")
    finally:
        hlo_audit.parse_hlo_module = real_parse
    recs = [json.loads(l) for l in open(log)] if os.path.exists(log) else []
    advisory = [r for r in recs if r.get("kind") == "sharp_edge"
                and "hlo_audit failed (advisory)" in (r.get("message") or "")]
    if out is not None and out != 32.0:
        n_errors += 1
        print(f"    FAILED: compile under corrupted auditor returned {out}")
    elif out is not None and not advisory:
        n_errors += 1
        print(f"    FAILED: no advisory sharp_edge in the event log "
              f"(kinds={sorted({r.get('kind') for r in recs})})")
    elif out is not None:
        print("    advisory OK: compile unharmed (result exact), sharp_edge "
              f"recorded: {advisory[0]['message'][:72]}")

    print(f"\nlint_traces --hlo: {n_errors} error(s)")
    return n_errors


def _static_smoke() -> int:
    """--static: the static trace planner smoke (ISSUE 10). Three parts:

    1. **Liveness/OOM prediction**: the GPT-tiny forward and fwd+bwd
       pipelines compile with ``instrument="memory"``; the entry's
       statically predicted peak must sit within 15% of the measured
       high-water (``bytes_in_use`` where the backend reports it; on the
       CPU plugin, the planner's eager-allocation total vs the hook's
       cumulative estimate — same quantity, same tolerance).
    2. **Collective-schedule safety**: an fsdp4·tp2-shaped gradient trace
       certifies (both mesh axes present, grad's reduce_scatter included);
       an uncertified same-axis reorder MUST be flagged, a certified legal
       one MUST pass; seeded-bad donation/alias traces must each trip their
       sanitizer rule.
    3. **Planner-guided de-opt**: under the chaos ``oom@<3`` seam (a
       deterministic memory ceiling that keeps OOMing below ladder level 3)
       with ``THUNDER_TPU_HBM_BYTES`` between the padded and exact-shape
       predicted peaks, the ladder must jump L0→L3 in ONE recompile —
       strictly fewer failed XLA compiles than HEAD's blind climb (which
       pays one per level: 4 compiles to reach L3).
    """
    import json
    import tempfile

    os.environ.setdefault("THUNDER_TPU_RETRY_BACKOFF_S", "0")

    import numpy as np
    import thunder_tpu as ttpu
    import thunder_tpu.clang as clang
    import thunder_tpu.core.prims as tprims
    from thunder_tpu.analysis import Severity, certify, plan_liveness, verify
    from thunder_tpu.analysis import schedule as sched_mod
    from thunder_tpu.core import devices, dtypes
    from thunder_tpu.core.proxies import TensorProxy
    from thunder_tpu.core.trace import TraceCtx, from_trace, tracectx
    from thunder_tpu.distributed import prims as dist
    from thunder_tpu.models import gpt as m
    from thunder_tpu.observability.instrument import instrument_reports

    n_errors = 0
    rng = np.random.RandomState(0)
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    # -- 1. liveness prediction vs measured high-water ------------------------
    workloads = [
        ("gpt-fwd", ttpu.jit(lambda p, i: m.forward(p, i, cfg),
                             executors=["jax"], instrument="memory"),
         (params, idx)),
        ("gpt-fwd+bwd", ttpu.value_and_grad(
            lambda p, i, t: m.loss_fn(p, i, t, cfg),
            executors=["jax"], instrument="memory"),
         (params, idx, tgt)),
    ]
    for name, jf, wargs in workloads:
        jf(*wargs)
        entry = jf._lc_cs.cache_entries[0]
        predicted_peak = entry.stats.predicted_peak_bytes
        rep = next((r for r in instrument_reports(jf)
                    if r["hook"] == "MemoryHighWater"), None)
        if predicted_peak is None or rep is None:
            # The planner is advisory at compile time (degrades to None),
            # but the smoke's whole job is to gate it: count the failure
            # instead of crashing the gate script.
            n_errors += 1
            print(f"    FAILED: {name}: planner produced no prediction "
                  f"(predicted_peak={predicted_peak}, memory hook="
                  f"{'present' if rep else 'absent'})")
            continue
        plan = plan_liveness(entry.computation_traces[-1], include_rows=False)
        if rep["exact"]:
            predicted, measured, what = predicted_peak, rep["peak_bytes"], "peak"
        else:
            # CPU plugin: no bytes_in_use — the hook's estimate is the
            # cumulative produced-bytes total, compared against the plan's
            # eager-allocation total (same quantity, statically derived).
            predicted, measured, what = (
                plan.eager_alloc_bytes, rep["peak_bytes"], "eager-alloc",
            )
        err = abs(predicted - measured) / max(measured, 1)
        line = (f"{name}: predicted {what} {predicted / 1e6:.2f} MB vs measured "
                f"{measured / 1e6:.2f} MB ({err * 100:+.1f}%), "
                f"static peak {predicted_peak / 1e6:.2f} MB")
        if err > 0.15:
            n_errors += 1
            print(f"    FAILED (OOM-misprediction >15%): {line}")
        else:
            print(f"    {line}")

    # -- 2. schedule certificate + sanitizer seeded-bads ----------------------
    print("--- static smoke: fsdp4-tp2 schedule certificate")

    def _cpu_t(shape, name=None):
        return TensorProxy(name=name, shape=shape, dtype=dtypes.float32,
                           device=devices.Device("cpu"))

    from thunder_tpu.api import trace_program
    from thunder_tpu.core.proxies import DistParallelType
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.transforms.autodiff import grad_transform
    from thunder_tpu.transforms.common import dce

    w = rng.randn(4, 8).astype(np.float32)  # fsdp shard of a (16, 8) weight
    x = rng.randn(4, 8).astype(np.float32)

    def fsdp_tp_loss(w_shard, xv):
        w_full = dist.synchronize(w_shard, "fsdp", 4, "fsdp")
        h = clang.matmul(xv, clang.transpose(w_full, 0, 1))
        h = dist.all_reduce(h, "tp", 2)
        return clang.mean(clang.mul(h, h))

    _, comp = trace_program(fsdp_tp_loss, (w, x), {})
    comp = dce(comp)
    comp = grad_transform(comp, return_value=True)
    extrace = transform_for_execution(comp, resolve_executors(["jax"]))
    cert = sched_mod.stamp(extrace)
    axes = set(cert.axis_order)
    syms = [s.sym for s in cert.sites]
    if {"fsdp", "tp"} <= axes and "reduce_scatter" in syms:
        print(f"    certificate OK: {len(cert.sites)} sites on axes "
              f"{sorted(axes)}, grad reduce_scatter present, "
              f"{len(cert.movable_sites())} movable")
    else:
        n_errors += 1
        print(f"    FAILED: certificate incomplete (axes={axes}, syms={syms})")
    if any(d.severity >= Severity.ERROR for d in verify(extrace)):
        n_errors += 1
        print("    FAILED: planner rules fired on the clean fsdp-tp trace")

    # Uncertified reorder of two same-axis collectives must be flagged.
    coll_idx = [s.index for s in cert.sites if s.axis == "fsdp"]
    if len(coll_idx) >= 2:
        bad = from_trace(extrace)
        bs = list(extrace.bound_symbols)
        i, j = coll_idx[0], coll_idx[1]
        bs[i], bs[j] = bs[j], bs[i]
        bad.bound_symbols = bs
        diags = verify(bad, pass_name="uncertified reorder pass")
        if any(d.rule == "sched.uncertified-reorder" for d in diags):
            print("    uncertified same-axis reorder flagged OK")
        else:
            n_errors += 1
            print("    FAILED: uncertified collective reorder NOT flagged")
    else:
        n_errors += 1
        print("    FAILED: expected >=2 fsdp collectives to exercise reorder")

    # Seeded-bad donation/alias traces: each sanitizer rule must fire.
    def _seeded_bads():
        t1 = TraceCtx()
        with tracectx(t1):
            a = _cpu_t((4, 4))
            t1.args = (a,)
            out = clang.mul(a, a)
            tprims.python_return(out)
            t1.output = out
        t1.tags["donated_inputs"] = (a.name,)
        t1.tags["rerun_reads_inputs"] = True
        yield "donation.use-after-donation", t1

        t2 = TraceCtx()
        with tracectx(t2):
            a = _cpu_t((4, 4))
            t2.args = (a,)
            tprims.python_return(a)
            t2.output = a
        t2.tags["donated_inputs"] = (a.name,)
        yield "donation.donated-output", t2

        t3 = TraceCtx()
        with tracectx(t3):
            src = _cpu_t((4, 4))
            dst = _cpu_t((4, 4))
            t3.args = (src, dst)
            written = _cpu_t((4, 4))
        t3.bound_symbols.append(tprims.copy_.bind(src, dst, output=written))
        with tracectx(t3):
            tprims.python_return(dst)
        t3.output = dst
        yield "alias.entry-aliasing", t3

    for rule_id, trc in _seeded_bads():
        diags = verify(trc)
        if any(d.rule == rule_id and d.severity >= Severity.ERROR for d in diags):
            print(f"    {rule_id} fired on seeded-bad trace OK")
        else:
            n_errors += 1
            print(f"    FAILED: {rule_id} did not fire on its seeded-bad trace")

    # -- 3. planner-guided de-opt ladder under the chaos oom ceiling ----------
    print("--- static smoke: de-opt ladder jump under oom@<3")
    from thunder_tpu.analysis.liveness import predict_level_peaks

    xb = rng.randn(100, 64).astype(np.float32)  # batch 100 -> pow2 bucket 128
    wb = rng.randn(64, 64).astype(np.float32)

    def chain(xv, wv):
        h = clang.matmul(xv, wv)
        h = clang.tanh(h)
        h = clang.matmul(h, wv)
        return clang.sum(clang.mul(h, h))

    baseline = float(np.asarray(
        ttpu.jit(chain, executors=["jax"])(xb, wb)
    ))

    probe = ttpu.jit(chain, cache="symbolic values", symbolic_dims={0: (0,)},
                     executors=["jax"])
    probe(xb, wb)
    probe_entry = probe._lc_cs.cache_entries[0]
    peaks = predict_level_peaks(
        probe_entry.computation_traces[-1],
        sym_spec=probe_entry.sym_spec,
        true_extents=probe_entry.last_true_extents,
    )
    if not (peaks[3] and peaks[1] and peaks[3] < peaks[1]):
        n_errors += 1
        print(f"    FAILED: exact-shape peak should undercut padded ({peaks})")
        print(f"\nlint_traces --static: {n_errors} error(s)")
        return n_errors
    capacity = (peaks[1] + peaks[3]) // 2
    os.environ["THUNDER_TPU_HBM_BYTES"] = str(int(capacity))
    log = os.path.join(tempfile.mkdtemp(prefix="ttpu_static_"), "events.jsonl")
    try:
        jf = ttpu.jit(chain, cache="symbolic values", symbolic_dims={0: (0,)},
                      executors=["jax"], chaos="oom@<3*inf", events=log)
        out = float(np.asarray(jf(xb, wb)))
        cs = jf._lc_cs
        level = jf._lc_cd._deopt_level
        deopts = [json.loads(l) for l in open(log)
                  if json.loads(l).get("kind") == "compile_deopt"]
        blind_compiles = 1 + 3  # HEAD pays one failed compile per level to L3
        ok = (
            abs(out - baseline) < 1e-3 * max(abs(baseline), 1.0)
            and level == 3
            and cs.compile_count < blind_compiles
            and len(deopts) == 1
            and deopts[0].get("skipped_levels") == [1, 2]
            and deopts[0].get("predicted_peak_bytes")
        )
        if ok:
            print(f"    ladder jump OK: L0 -> L3 in {cs.compile_count} compiles "
                  f"(blind HEAD: {blind_compiles}), skipped {deopts[0]['skipped_levels']}, "
                  f"predicted {deopts[0]['predicted_peak_bytes'] / 1e3:.1f} KB vs "
                  f"capacity {capacity / 1e3:.1f} KB")
        else:
            n_errors += 1
            print(f"    FAILED: level={level} compiles={cs.compile_count} "
                  f"(blind={blind_compiles}) deopts={deopts} out={out} "
                  f"baseline={baseline}")
    finally:
        os.environ.pop("THUNDER_TPU_HBM_BYTES", None)

    print(f"\nlint_traces --static: {n_errors} error(s)")
    return n_errors


def _schedule_smoke() -> int:
    """--schedule: the comm-scheduler smoke (ISSUE 13). Four parts:

    1. **Scheduling the fsdp4·tp2 grad trace**: the explicit-collective
       FSDP×TP fw+bw trace schedules with ≥1 hoist, re-certifies with the
       identical per-axis collective order, passes the full verifier, and
       the post-schedule prediction shows hidden wire > 0 for the top
       movable fsdp ``synchronize`` AND for a grad ``reduce_scatter``.
    2. **Liveness-constrained placement**: with ``capacity_bytes`` set
       between the unscheduled and fully-hoisted predicted peaks, the
       hoists must back off to placements whose predicted peak fits —
       never schedule a predicted OOM.
    3. **Bad schedule demotes cleanly** (chaos ``sched_bad``): a corrupted
       placement is caught by the pass's own interval validation; the
       compile falls back to the unscheduled certified order with a
       ``sharp_edge`` event (replay-correlated), and the result is
       unchanged.
    4. **De-opt ladder**: a chaos ``compile_fail`` climbs to L1, where the
       scheduler (like fusion) is disabled — the recovery path compiles
       without it instead of wedging.
    """
    import json
    import tempfile

    os.environ.setdefault("THUNDER_TPU_RETRY_BACKOFF_S", "0")

    import numpy as np
    import thunder_tpu as ttpu
    import thunder_tpu.clang as clang
    from thunder_tpu.analysis import Severity, verify
    from thunder_tpu.analysis import schedule as sched_mod
    from thunder_tpu.analysis.liveness import plan_liveness
    from thunder_tpu.api import trace_program
    from thunder_tpu.distributed import prims as dist
    from thunder_tpu.executors.passes import transform_for_execution
    from thunder_tpu.extend import resolve_executors
    from thunder_tpu.resilience import chaos as chaos_mod
    from thunder_tpu.transforms.autodiff import grad_transform
    from thunder_tpu.transforms.common import dce
    from thunder_tpu.transforms.comm_schedule import schedule_collectives

    n_errors = 0
    rng = np.random.RandomState(0)
    layers, d, B, fsdp_g, tp_g = 3, 64, 16, 4, 2
    ws = [rng.randn(d // fsdp_g, d).astype(np.float32) for _ in range(layers)]
    x = rng.randn(B, d).astype(np.float32)

    def fsdp_tp_loss(*flat_in):
        *w_shards, xv = flat_in
        h = xv
        for w_shard in w_shards:
            w_full = dist.synchronize(w_shard, "fsdp", fsdp_g, "fsdp")
            h = clang.matmul(h, clang.transpose(w_full, 0, 1))
            h = dist.all_reduce(h, "tp", tp_g, op="avg")
            h = clang.tanh(h)
        return clang.mean(clang.mul(h, h))

    def build():
        _, comp = trace_program(fsdp_tp_loss, (*ws, x), {})
        comp = dce(comp)
        comp = grad_transform(comp, return_value=True)
        return transform_for_execution(comp, resolve_executors(["jax"]))

    # -- 1. schedule + recertify + hidden>0 for sync and reduce_scatter -------
    print("--- schedule smoke: fsdp4-tp2 grad trace through the scheduler")
    extrace = build()
    cert0 = sched_mod.stamp(extrace)
    scheduled, rep = schedule_collectives(extrace, device="cpu")
    pred = sched_mod.predict_overlap(scheduled, device="cpu")
    sync_sites = [s for s in pred.sites if s.sym == "synchronize"]
    rs_sites = [s for s in pred.sites if s.sym == "reduce_scatter"]
    top_sync = max(sync_sites, key=lambda s: s.hidden_us, default=None)
    top_rs = max(rs_sites, key=lambda s: s.hidden_us, default=None)
    cert1 = sched_mod.certify(scheduled)
    errors = [d for d in verify(scheduled) if d.severity >= Severity.ERROR]
    ok = (
        rep is not None and rep.moves >= 1
        and cert1.axis_order == cert0.axis_order
        and scheduled.tags.get("collective_order") == cert1.axis_order
        and not errors
        and top_sync is not None and top_sync.hidden_us > 0
        and top_rs is not None and top_rs.hidden_us > 0
    )
    if ok:
        print(f"    scheduled OK: {rep.moves} move(s), axis order preserved, "
              f"verifier clean; hidden {top_sync.label()}="
              f"{top_sync.hidden_us:.1f}us, {top_rs.label()}="
              f"{top_rs.hidden_us:.1f}us (exposed "
              f"{rep.exposed_pct_before:.0f}% -> {rep.exposed_pct_after:.0f}%)")
    else:
        n_errors += 1
        print(f"    FAILED: moves={getattr(rep, 'moves', None)} "
              f"order_ok={cert1.axis_order == cert0.axis_order} "
              f"errors={errors} sync={top_sync} rs={top_rs}")

    # -- 2. liveness back-off under a capacity squeeze ------------------------
    # Forward-only chain: the grad trace's peak sits in the backward (big
    # cotangents), so the squeeze is demonstrated where gathers dominate —
    # hoisting every synchronize materializes all full weights at once.
    print("--- schedule smoke: capacity squeeze forces hoist back-off")

    def build_fwd():
        _, comp = trace_program(fsdp_tp_loss, (*ws, x), {})
        comp = dce(comp)
        return transform_for_execution(comp, resolve_executors(["jax"]))

    fwd0 = build_fwd()
    sched_free, rep_free = schedule_collectives(fwd0, device="cpu")
    p0 = plan_liveness(fwd0, include_rows=False).peak_bytes
    p1 = plan_liveness(sched_free, include_rows=False).peak_bytes
    if not p1 > p0:
        n_errors += 1
        print(f"    FAILED: hoisting should raise the predicted peak "
              f"({p0} -> {p1})")
    else:
        cap = (p0 + p1) // 2
        sched_cap, rep_cap = schedule_collectives(
            build_fwd(), device="cpu", capacity_bytes=cap
        )
        p_cap = plan_liveness(sched_cap, include_rows=False).peak_bytes
        if rep_cap is not None and rep_cap.backoffs >= 1 and p_cap <= cap:
            print(f"    back-off OK: free peak {p1 / 1e3:.1f}KB > capacity "
                  f"{cap / 1e3:.1f}KB -> {rep_cap.backoffs} back-off(s), "
                  f"constrained peak {p_cap / 1e3:.1f}KB fits")
        else:
            n_errors += 1
            print(f"    FAILED: backoffs={getattr(rep_cap, 'backoffs', None)} "
                  f"peak {p_cap} vs capacity {cap} (free {p1})")

    # -- 3. chaos sched_bad: corrupted placement demotes to unscheduled -------
    print("--- schedule smoke: sched_bad chaos falls back cleanly")
    from thunder_tpu.observability import events as obs_events

    log = os.path.join(tempfile.mkdtemp(prefix="ttpu_sched_"), "events.jsonl")
    extrace = build()
    order_before = sched_mod.certify(extrace).axis_order
    with obs_events.event_scope(obs_events.log_for_path(log)):
        with chaos_mod.chaos_scope("sched_bad*1"):
            fell_back, rep_bad = schedule_collectives(extrace, device="cpu")
    recs = [json.loads(l) for l in open(log)]
    kinds = [r.get("kind") for r in recs]
    injected = any(r.get("kind") == "fault_injected" and r.get("seam") == "sched_bad"
                   for r in recs)
    rejected = any(r.get("kind") == "sharp_edge"
                   and r.get("policy") == "comm_schedule_fallback"
                   for r in recs)
    # The replay correlation rule itself must accept the fallback as the
    # seam's recovery (FAULT_RECOVERY_KINDS sched_bad -> sharp_edge).
    from thunder_tpu.analysis.events import replay_events

    _, replay_diags = replay_events(log)
    uncorrelated = [d for d in replay_diags
                    if d.rule == "events.unrecovered-fault"]
    ok = (
        fell_back is extrace and rep_bad is None
        and sched_mod.certify(fell_back).axis_order == order_before
        and injected and rejected and not uncorrelated
    )
    if ok:
        print("    sched_bad OK: corrupted placement rejected, unscheduled "
              "order kept, fault_injected + sharp_edge correlated")
    else:
        n_errors += 1
        print(f"    FAILED: fell_back={fell_back is extrace} rep={rep_bad} "
              f"injected={injected} rejected={rejected} kinds={kinds}")

    # -- 4. compile_fail climbs the ladder; L1 compiles without the scheduler -
    print("--- schedule smoke: compile_fail de-opts to L1 (scheduler off)")
    xb = rng.randn(8, 8).astype(np.float32)

    def chain(xv):
        h = clang.tanh(clang.matmul(xv, xv))
        return clang.sum(clang.mul(h, h))

    baseline = float(np.asarray(ttpu.jit(chain, executors=["jax"])(xb)))
    jf = ttpu.jit(chain, executors=["jax"], chaos="compile_fail*1;seed=3")
    out = float(np.asarray(jf(xb)))
    level = jf._lc_cd._deopt_level
    if abs(out - baseline) < 1e-6 and level == 1:
        print(f"    de-opt OK: recovered at L1 (fusion/donation/comm-schedule "
              f"off), result matches baseline")
    else:
        n_errors += 1
        print(f"    FAILED: level={level} out={out} baseline={baseline}")

    print(f"\nlint_traces --schedule: {n_errors} error(s)")
    return n_errors


def _chaos_smoke() -> int:
    """--chaos: the resilience smoke (ISSUE 6 satellite). Runs the GPT
    gradient pipeline under a canned fault schedule — executor kernel raise,
    XLA compile failure, device OOM, NaN poisoning — asserting every fault
    recovers to the un-faulted baseline (bitwise) or raises the typed error
    naming its seam, and that the JSONL log carries the correlated
    ``fault_injected`` → degradation event pair for each injection (the
    replay's ``events.unrecovered-fault`` rule). Returns the error count."""
    import tempfile

    os.environ.setdefault("THUNDER_TPU_RETRY_BACKOFF_S", "0")

    import numpy as np
    import thunder_tpu as ttpu
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events
    from thunder_tpu.core import dtypes
    from thunder_tpu.extend import OperatorExecutor, get_executor, register_executor
    from thunder_tpu.models import gpt as m
    from thunder_tpu.resilience import NonFiniteOutputError, chaos, demotion

    demotion.clear_quarantine()
    rng = np.random.RandomState(0)
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    loss = lambda p, i, t: m.loss_fn(p, i, t, cfg)  # noqa: E731

    log = os.path.join(tempfile.mkdtemp(prefix="ttpu_chaos_"), "events.jsonl")
    n_errors = 0

    def flat(out):
        from thunder_tpu.core.pytree import tree_flatten

        return [np.asarray(x) for x in tree_flatten(out)[0]]

    print("--- chaos smoke: un-faulted baseline")
    baseline = flat(ttpu.value_and_grad(loss, executors=["jax"])(params, idx, tgt))

    # A chaos-armed smoke executor claiming the erf prim (inside the GPT
    # MLP's gelu): the kernel-raise seam for an environment with no TPU
    # kernels (pallasex/flashex carry the same seam on real hardware). The
    # impl delegates to the jax executor's, so even an un-demoted claim is
    # bitwise-identical to the baseline.
    from thunder_tpu.core.prims import PrimIDs

    smoke_ex = get_executor("chaos_smoke")
    if smoke_ex is None:
        smoke_ex = OperatorExecutor("chaos_smoke")
        register_executor(smoke_ex)
        _jax_erf = get_executor("jax").get_impl(PrimIDs.ERF)

        def _smoke_erf(a, _jax_erf=_jax_erf):
            chaos.kernel_seam("chaos_smoke", "erf")
            return _jax_erf(a)

        smoke_ex.register_implementation(PrimIDs.ERF, fn=_smoke_erf)

    schedules = [
        ("kernel_raise (executor demotion)", ["chaos_smoke", "jax"],
         "kernel_raise@chaos_smoke*1", None),
        ("compile_fail + oom (de-opt ladder)", ["jax"], "compile_fail*1;oom*1", None),
        ("nan poison (isfinite guard)", ["jax"], "nan@matmul*1", "rerun-instrumented"),
    ]
    for name, executors, spec, on_nan in schedules:
        print(f"--- chaos smoke: {name} [{spec}]")
        jf = ttpu.value_and_grad(
            loss, executors=executors, events=log, chaos=spec, on_nan=on_nan
        )
        try:
            out = flat(jf(params, idx, tgt))
        except NonFiniteOutputError as e:
            if on_nan is None:
                n_errors += 1
                print(f"    FAILED: unexpected NonFiniteOutputError: {e}")
            else:
                print(f"    recovered loudly: {type(e).__name__} "
                      f"attributed to {e.symbol!r}")
            continue
        except Exception as e:  # an unrecovered fault escaped: that IS the failure
            n_errors += 1
            print(f"    FAILED (unrecovered fault): {type(e).__name__}: {e}")
            continue
        if on_nan is not None:
            n_errors += 1
            print("    FAILED: nan poison did not trip the isfinite guard")
        elif len(out) != len(baseline) or any(
            not np.array_equal(a, b) for a, b in zip(out, baseline)
        ):
            n_errors += 1
            print("    FAILED: recovered run is not bitwise-equal to baseline")
        else:
            print("    recovered, bitwise-equal to baseline")

    print("--- chaos smoke: event-log replay (correlation rule)")
    # Recompiles ARE the recovery mechanism under chaos (every demotion and
    # de-opt recompiles), so the storm heuristic gets headroom here; the
    # correlation rule is what this replay is for.
    summary, diags = replay_events(log, storm_threshold=16)
    print(format_replay(summary, diags))
    n_errors += sum(1 for d in diags if d.severity >= Severity.ERROR)
    if not summary.get("faults_injected"):
        n_errors += 1
        print("    FAILED: no fault_injected events recorded")
    demotion.clear_quarantine()
    print(f"\nlint_traces --chaos: {n_errors} error(s)")
    return n_errors


_SOAK_REQUIRED_KEYS = (
    "metric", "value", "unit", "seed", "n_devices", "mesh", "model", "steps",
    "soak_goodput_tokens_per_sec", "soak_tokens_per_sec",
    "soak_ideal_tokens_per_sec", "soak_goodput_ratio",
    "resilience_overhead_pct", "soak_wall_s", "soak_recovery_per_fault_s",
    "soak_faults_injected",
    "soak_fault_seams", "soak_overlapping_pairs", "soak_decisions",
    "soak_unrecovered", "soak_unactuated",
    # Tiered checkpointing (ISSUE 14).
    "checkpoint_stall_ms_per_step", "snapshot_every", "soak_snapshots",
    "soak_restore_tiers", "soak_restore_fallthroughs",
    # Live ops plane (ISSUE 15).
    "soak_ops_port", "soak_anomalies", "soak_anomalies_total",
    "soak_detection_lead", "soak_decisions_citing_anomaly",
    "soak_undetected_detector_classes", "soak_flightrec_dumps",
    "soak_flightrec_invalid", "soak_flightrec_missing",
)

# The hot loop's amortized checkpoint cost must stay snapshot-shaped (a
# device→host copy every few steps). A synchronous disk save leaking back
# onto the hot path costs ~100ms+ per cadence hit — far past this cap even
# on a loaded CI machine.
_SOAK_STALL_MS_PER_STEP_CAP = 25.0

# The four autopilot policy classes the smoke must see decided at least
# once (the schedule's REQUIRED_SEAMS guarantee the triggering faults).
_SOAK_POLICY_CLASSES = (
    "elastic_resume", "quarantine_rerun", "deopt_escalate", "checkpoint_halt",
)


def _torn_fallthrough_check() -> int:
    """Deterministic torn-write disk fall-through (ISSUE 14 satellite): a
    ``snap_torn`` background flush leaves its step directory WITHOUT the
    META commit marker; the tiered restore must skip the incomplete step
    and land on the older complete one — asserted from the replayed event
    log, not from in-process state. Returns the error count."""
    import json
    import tempfile

    import numpy as np

    import thunder_tpu.monitor as monitor
    from thunder_tpu.analysis.events import replay_events
    from thunder_tpu.resilience import chaos, elastic
    from thunder_tpu.resilience.preemption import CheckpointManager

    tmp = tempfile.mkdtemp(prefix="ttpu_torn_")
    log = os.path.join(tmp, "ev.jsonl")
    n_errors = 0
    monitor.set_event_log(log)
    try:
        mgr = CheckpointManager(os.path.join(tmp, "ck"), backoff_s=0,
                                async_flush=True)
        state = {"p": np.arange(8, dtype=np.float32)}
        mgr.save(state, 10)
        with chaos.chaos_scope("snap_torn"):
            mgr.snapshot(state, 20, flush=True)
            mgr.close()  # drain: the torn flush's events are in the log
        _, meta, tier, _tried = elastic.tiered_restore(mgr)
    finally:
        monitor.set_event_log(None)
    if not (tier == "disk" and meta["step"] == 10):
        n_errors += 1
        print(f"    FAILED: torn fall-through restored {tier}@{meta['step']} "
              f"(want disk@10)")
    summary, diags = replay_events(log)
    records = [json.loads(line) for line in open(log)]
    torn_flush = any(r["kind"] == "snapshot_flush" and not r["ok"]
                     and r.get("reason") == "torn" for r in records)
    skipped = any(r["kind"] == "checkpoint_restore" and not r["ok"]
                  for r in records)
    if not (torn_flush and skipped):
        n_errors += 1
        print(f"    FAILED: torn-write log shape (torn_flush={torn_flush}, "
              f"incomplete-skip={skipped})")
    if summary.get("unrecovered_faults"):
        n_errors += 1
        print(f"    FAILED: snap_torn unrecovered: "
              f"{summary['unrecovered_faults']}")
    if not n_errors:
        print("    torn-write fall-through OK: flush tore at step 20, "
              "restore skipped it and landed on disk@10")
    return n_errors


def _soak_smoke() -> int:
    """--soak: the fleet-autopilot soak smoke (ISSUE 11 satellite). Runs a
    short deterministic ``scripts/soak_fleet.py --smoke`` on the 8-device
    virtual mesh and asserts: zero unrecovered faults, zero unactuated
    decisions, at least one decision of EVERY policy class, every required
    seam kind injected, and a per-fault recovery cost within the soak
    noise floor of the committed ``SOAK_r*.json`` round. Tiered
    checkpointing (ISSUE 14): also asserts a bounded
    ``checkpoint_stall_ms_per_step``, at least one RAM-tier and one
    disk-tier restore, a restore that FELL THROUGH an invalid tier, and
    (in-process) the deterministic torn-write disk fall-through — all from
    replayed event logs. Full runs additionally gate the committed series
    with ``perf_report --gate``. Returns the error count."""
    import glob
    import json
    import subprocess
    import tempfile

    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    repo_root = os.path.dirname(scripts_dir)
    out_path = os.path.join(tempfile.mkdtemp(prefix="ttpu_soak_smoke_"), "soak.json")
    cmd = [sys.executable, os.path.join(scripts_dir, "soak_fleet.py"),
           "--smoke", "--seed", "7", "--out", out_path]
    print("--- soak smoke: " + " ".join(cmd))
    n_errors = 0
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1500)
    for line in r.stderr.strip().splitlines()[-20:]:
        print(f"    {line}")
    if r.returncode != 0:
        print(f"    FAILED: soak_fleet exited {r.returncode}")
        return 1
    with open(out_path) as f:
        result = json.load(f)

    missing = [k for k in _SOAK_REQUIRED_KEYS if k not in result]
    if missing:
        n_errors += 1
        print(f"    FAILED: soak JSON missing keys: {missing}")
    else:
        print(f"    schema OK ({len(_SOAK_REQUIRED_KEYS)} required keys)")

    if result.get("soak_unrecovered") or result.get("soak_unactuated"):
        n_errors += 1
        print(f"    FAILED: unrecovered={result.get('soak_unrecovered')} "
              f"unactuated={result.get('soak_unactuated')}")
    else:
        print("    correlation OK: zero unrecovered faults, zero unactuated "
              "decisions")

    decisions = result.get("soak_decisions") or {}
    absent = [c for c in _SOAK_POLICY_CLASSES if not decisions.get(c)]
    if absent:
        n_errors += 1
        print(f"    FAILED: policy classes never decided: {absent} "
              f"(got {decisions})")
    else:
        print("    policy coverage OK: " + ", ".join(
            f"{c}×{decisions[c]}" for c in _SOAK_POLICY_CLASSES))

    seams = result.get("soak_fault_seams") or {}
    if len(seams) < 5 or not result.get("soak_overlapping_pairs"):
        n_errors += 1
        print(f"    FAILED: schedule diversity (seams={sorted(seams)}, "
              f"overlaps={result.get('soak_overlapping_pairs')})")
    else:
        print(f"    schedule OK: {result.get('soak_faults_injected')} faults "
              f"across {len(seams)} seam kinds, "
              f"{result['soak_overlapping_pairs']} overlapping pair(s)")

    # Tiered checkpointing (ISSUE 14): the soak's own replay computed these
    # from its event log (soak_fleet derives them via replay_events).
    stall = result.get("checkpoint_stall_ms_per_step")
    if not isinstance(stall, (int, float)) or not (
            0.0 < stall <= _SOAK_STALL_MS_PER_STEP_CAP):
        n_errors += 1
        print(f"    FAILED: checkpoint_stall_ms_per_step={stall} not in "
              f"(0, {_SOAK_STALL_MS_PER_STEP_CAP}] — snapshots missing, or "
              f"disk IO leaked back onto the hot path")
    else:
        print(f"    stall OK: {stall:.2f} ms/step over "
              f"{result.get('soak_snapshots')} snapshots")
    tiers = result.get("soak_restore_tiers") or {}
    ram = (tiers.get("local") or 0) + (tiers.get("peer") or 0)
    if not ram or not tiers.get("disk"):
        n_errors += 1
        print(f"    FAILED: restore-tier coverage {tiers} (need >=1 RAM-tier "
              f"and >=1 disk-tier restore)")
    elif not result.get("soak_restore_fallthroughs"):
        n_errors += 1
        print(f"    FAILED: no restore fell through an invalid tier "
              f"(snap_corrupt must force the checksum gate; tiers={tiers})")
    else:
        print(f"    tiers OK: " + ", ".join(
            f"{t}×{n}" for t, n in sorted(tiers.items()))
            + f"; {result['soak_restore_fallthroughs']} fall-through(s)")
    # Live ops plane (ISSUE 15): the detectors must have flagged every
    # detector-covered fault class, an anomaly must PRECEDE the decision
    # citing it (positive detection lead), and every timeout/halt must have
    # left a schema-valid flight-recorder dump.
    anomalies = result.get("soak_anomalies") or {}
    if result.get("soak_undetected_detector_classes") or not anomalies:
        n_errors += 1
        print(f"    FAILED: detector coverage (anomalies={anomalies}, "
              f"missed={result.get('soak_detector_classes_missed')})")
    elif not (isinstance(result.get("soak_detection_lead"), (int, float))
              and result["soak_detection_lead"] > 0):
        n_errors += 1
        print(f"    FAILED: detection lead "
              f"{result.get('soak_detection_lead')} not > 0 (no decision "
              f"cited a preceding anomaly)")
    else:
        print("    detectors OK: " + ", ".join(
            f"{k}×{n}" for k, n in sorted(anomalies.items()))
            + f"; lead {result['soak_detection_lead']:.2f}s over "
            f"{result.get('soak_decisions_citing_anomaly')} cited decision(s)")
    if (result.get("soak_flightrec_invalid")
            or result.get("soak_flightrec_missing")
            or not result.get("soak_flightrec_dumps")):
        n_errors += 1
        print(f"    FAILED: flight recorder "
              f"(dumps={result.get('soak_flightrec_dumps')}, "
              f"invalid={result.get('soak_flightrec_invalid')}, "
              f"missing={result.get('soak_flightrec_missing')})")
    else:
        print(f"    flight recorder OK: "
              + ", ".join(f"{r}×{n}" for r, n in sorted(
                  (result.get('soak_flightrec_by_reason') or {}).items()))
              + " dump(s), all schema-valid")

    n_errors += _torn_fallthrough_check()

    # Goodput sanity vs the committed round. The goodput RATIO swings with
    # the machine's ideal step time (the CPU mesh cannot hold it steady
    # run to run), so the portable comparator is the recovery cost charged
    # per fault — wall time beyond ideal-speed useful steps, per injection
    # — bounded by the soak noise floor (perf_report._SOAK_NOISE_FLOORS),
    # doubled for the smoke's shorter run (one-off rebuild costs amortize
    # over fewer faults).
    committed = sorted(glob.glob(os.path.join(repo_root, "SOAK_r*.json")))
    goodput = result.get("soak_goodput_tokens_per_sec")
    per_fault = result.get("soak_recovery_per_fault_s")
    if not isinstance(goodput, (int, float)) or goodput <= 0:
        n_errors += 1
        print(f"    FAILED: no usable goodput ({goodput})")
    elif committed and isinstance(per_fault, (int, float)):
        if scripts_dir not in sys.path:
            sys.path.insert(0, scripts_dir)
        from perf_report import noise_floor

        with open(committed[-1]) as f:
            ref = json.load(f).get("soak_recovery_per_fault_s")
        floor = 2 * noise_floor("per_fault_s", "soak_goodput")
        if isinstance(ref, (int, float)) and abs(per_fault - ref) > floor:
            n_errors += 1
            print(f"    FAILED: recovery cost {per_fault:.2f}s/fault vs "
                  f"committed {ref:.2f} (floor ±{floor:.1f}s)")
        else:
            print(f"    goodput OK: {goodput:.0f} tok/s; recovery "
                  f"{per_fault:.2f}s/fault (committed {ref}, floor "
                  f"±{floor:.1f}s)")

    n_errors += _bench_history_gate("SOAK_r*.json")
    print(f"\nlint_traces --soak: {n_errors} error(s)")
    return n_errors


# The committed SOAK_POD schema (scripts/soak_pod.py — ISSUE 18): the
# federation invariants the smoke and the committed-round gate both read.
_POD_REQUIRED_KEYS = (
    "metric", "value", "unit", "n_devices", "n_slices", "mesh", "model",
    "steps", "soak_pod_goodput_tokens_per_sec", "soak_pod_wall_s",
    "soak_pod_degraded_steps", "soak_pod_degraded_tokens_per_sec",
    "soak_pod_full_width", "soak_pod_final_width", "soak_pod_min_width",
    "soak_pod_shrinks", "soak_pod_regrows", "soak_pod_restarts",
    "soak_pod_slice_loss_restores", "soak_pod_slice_loss_nonpeer_restores",
    "soak_pod_disk_restores_after_anchor", "soak_pod_restore_tiers",
    "soak_pod_decisions", "soak_pod_unrecovered", "soak_pod_unactuated",
    "soak_pod_replay_errors",
)


def _federation_smoke() -> int:
    """--federation: the slice-failure-domain smoke (ISSUE 18 satellite).
    Runs ``scripts/soak_pod.py --smoke`` — 2 emulated slices × 2 devices,
    one scripted whole-slice loss — and asserts the elastic cycle
    completed inside the CI budget: the fleet shrank (one shrink_dp,
    degraded steps at reduced width), trained through the loss, regrew to
    full DP width (one regrow_dp, final == full), the victim's state came
    back from the cross-slice buddy's PEER-RAM tier with disk untouched
    past the step-0 anchor, and the replayed ledger correlates clean (zero
    unrecovered / unactuated / replay errors, no process restart). Full
    runs additionally gate the committed ``SOAK_POD_r*.json`` round's
    absolute invariants via ``perf_report --gate``. Returns the error
    count."""
    import json
    import subprocess
    import tempfile
    import time

    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(tempfile.mkdtemp(prefix="ttpu_fed_smoke_"),
                            "pod.json")
    cmd = [sys.executable, os.path.join(scripts_dir, "soak_pod.py"),
           "--smoke", "--seed", "7", "--out", out_path]
    print("--- federation smoke: " + " ".join(cmd))
    n_errors = 0
    t0 = time.perf_counter()
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600)
    elapsed = time.perf_counter() - t0
    for line in r.stderr.strip().splitlines()[-12:]:
        print(f"    {line}")
    if r.returncode != 0:
        print(f"    FAILED: soak_pod exited {r.returncode}")
        return 1
    with open(out_path) as f:
        result = json.load(f)

    missing = [k for k in _POD_REQUIRED_KEYS if k not in result]
    if missing:
        n_errors += 1
        print(f"    FAILED: pod JSON missing keys: {missing}")
    else:
        print(f"    schema OK ({len(_POD_REQUIRED_KEYS)} required keys)")

    # The acceptance wall: shrink -> degraded training -> regrow, on CPU,
    # inside a minute (compiles for both widths included).
    if elapsed >= 60.0:
        n_errors += 1
        print(f"    FAILED: smoke took {elapsed:.1f}s (budget 60s)")
    else:
        print(f"    budget OK: shrink->train->regrow in {elapsed:.1f}s")

    full = result.get("soak_pod_full_width")
    if not (result.get("soak_pod_shrinks") == 1
            and result.get("soak_pod_regrows") == 1
            and result.get("soak_pod_degraded_steps", 0) > 0
            and result.get("soak_pod_min_width", full) < full
            and result.get("soak_pod_final_width") == full
            and not result.get("soak_pod_restarts")):
        n_errors += 1
        print(f"    FAILED: elastic cycle (shrinks="
              f"{result.get('soak_pod_shrinks')} regrows="
              f"{result.get('soak_pod_regrows')} degraded="
              f"{result.get('soak_pod_degraded_steps')} widths "
              f"{result.get('soak_pod_min_width')}->"
              f"{result.get('soak_pod_final_width')}/{full})")
    else:
        print(f"    elastic cycle OK: width {full}->"
              f"{result.get('soak_pod_min_width')}->{full}, "
              f"{result.get('soak_pod_degraded_steps')} degraded step(s)")

    if (not result.get("soak_pod_slice_loss_restores")
            or result.get("soak_pod_slice_loss_nonpeer_restores")
            or result.get("soak_pod_disk_restores_after_anchor")):
        n_errors += 1
        print(f"    FAILED: peer-tier proof (restores="
              f"{result.get('soak_pod_slice_loss_restores')} nonpeer="
              f"{result.get('soak_pod_slice_loss_nonpeer_restores')} "
              f"disk_after_anchor="
              f"{result.get('soak_pod_disk_restores_after_anchor')})")
    else:
        print(f"    peer-tier proof OK: tiers "
              f"{result.get('soak_pod_restore_tiers')}")

    if (result.get("soak_pod_unrecovered")
            or result.get("soak_pod_unactuated")
            or result.get("soak_pod_replay_errors")):
        n_errors += 1
        print(f"    FAILED: replay (unrecovered="
              f"{result.get('soak_pod_unrecovered')} unactuated="
              f"{result.get('soak_pod_unactuated')} errors="
              f"{result.get('soak_pod_replay_errors')})")
    else:
        print("    correlation OK: zero unrecovered faults, zero "
              "unactuated decisions")

    n_errors += _bench_history_gate("SOAK_POD_r*.json", min_rounds=1)
    print(f"\nlint_traces --federation: {n_errors} error(s)")
    return n_errors


def _ops_smoke() -> int:
    """--ops: live ops-plane smoke (ISSUE 15; docs/observability.md "ops
    plane"). Starts the per-host HTTP server against a chaos'd GPT step and
    asserts the four acceptance behaviors: /healthz flips degraded on a
    seeded straggler (streaming detectors), /metrics scrapes mid-run with
    host labels + the always-export drop counter, an injected hang leaves a
    schema-valid flight-recorder dump, and the measured ops-plane overhead
    stays under 1% of the step time (with exactly zero taps installed when
    the plane is off). Returns the error count."""
    import json
    import tempfile
    import time
    import urllib.error
    import urllib.request

    import thunder_tpu as ttpu
    import thunder_tpu.monitor as monitor
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import replay_events
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.observability import events as obs_events
    from thunder_tpu.observability import opsplane
    from thunder_tpu.observability.detect import DetectorConfig
    from thunder_tpu.resilience import chaos, watchdog
    from thunder_tpu.resilience.preemption import CheckpointManager, run_training

    n_errors = 0
    tmp = tempfile.mkdtemp(prefix="ttpu_ops_")
    fr_dir = os.path.join(tmp, "flightrec")
    plane = monitor.serve(port=0, flightrec_dir=fr_dir,
                          detectors=DetectorConfig(min_samples=6, cooldown=20))
    print(f"--- ops smoke: server on 127.0.0.1:{plane.port}")

    def get(route):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.port}{route}", timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 32)).astype(np.int32)
    jf = ttpu.jit(lambda p, i: m.forward(p, i, cfg), executors=["jax"])

    def step_fn(state):
        out = jf(params, idx)
        return state, float(np.asarray(out).mean())

    step_fn(None)  # compile outside the measured/chaos'd loop
    t0 = time.perf_counter()
    for _ in range(5):
        step_fn(None)
    step_s = (time.perf_counter() - t0) / 5

    code, body = get("/healthz")
    before = json.loads(body)["status"]

    # A chaos'd training run: clean baseline steps, then a seeded straggler
    # (sub-timeout slowdown inside the guarded step) the detectors must
    # flag; /metrics is scraped MID-RUN from the step callback.
    ccfg = chaos.ChaosConfig(rules=[], seed=0)
    scraped = {}

    def on_loss(step, loss):
        if step == 11:
            ccfg.rules.append(chaos.FaultRule(
                "straggler", target="step", count=6,
                delay_s=max(0.25, step_s * 4)))
        if step == 18:
            scraped["code"], scraped["body"] = get("/metrics")

    with chaos.chaos_scope(ccfg):
        run_training(step_fn, None, 24,
                     manager=CheckpointManager(os.path.join(tmp, "ck")),
                     watchdog_timeout_s=60.0, on_loss=on_loss)

    code, body = get("/healthz")
    after = json.loads(body)
    anomalies = [a.kind for a in plane.bank.recent_anomalies()]
    if before != "ok" or after["status"] == "ok" or not anomalies:
        n_errors += 1
        print(f"    FAILED: healthz did not flip on the straggler "
              f"(before={before}, after={after['status']}, "
              f"anomalies={anomalies})")
    else:
        print(f"    healthz OK: ok -> {after['status']} on anomalies "
              f"{sorted(set(anomalies))}")

    mtext = scraped.get("body") or ""
    if (scraped.get("code") != 200
            or "thunder_tpu_event_log_dropped_total" not in mtext
            or 'host="' not in mtext):
        n_errors += 1
        print(f"    FAILED: mid-run /metrics scrape (code="
              f"{scraped.get('code')}, drop-counter present: "
              f"{'thunder_tpu_event_log_dropped_total' in mtext}, "
              f"host label present: {'host=' in mtext})")
    else:
        print(f"    /metrics OK mid-run: {len(mtext.splitlines())} lines, "
              f"host-labelled, always-export drop counter present")

    # An injected hang must turn into a typed timeout AND a schema-valid
    # flight-recorder dump carrying its preceding context.
    with chaos.chaos_scope("collective_hang~30"):
        try:
            watchdog.guard_call(lambda: None, (), fn_name="gpt_step",
                                timeout_s=0.2)
            n_errors += 1
            print("    FAILED: injected hang did not raise")
        except watchdog.CollectiveTimeoutError:
            pass
    import glob as _glob

    dumps = _glob.glob(os.path.join(fr_dir, "*collective_timeout.jsonl"))
    if not dumps:
        n_errors += 1
        print("    FAILED: no flight-recorder dump for the hang")
    else:
        summary, diags = replay_events(dumps[-1])
        errs = [d for d in diags if d.severity >= Severity.ERROR]
        kinds = summary.get("kinds", {})
        if errs or not kinds.get("collective_timeout") \
                or not summary.get("flightrec_dumps"):
            n_errors += 1
            print(f"    FAILED: dump replay ({len(errs)} error(s), "
                  f"kinds={kinds})")
        else:
            print(f"    flight recorder OK: {os.path.basename(dumps[-1])} "
                  f"({summary['lines']} records, schema-valid, "
                  f"0 correlation errors)")
    code, body = get("/debug/flightrec")
    if code != 200 or not json.loads(body).get("path"):
        n_errors += 1
        print(f"    FAILED: /debug/flightrec ({code}: {body[:120]})")
    code, body = get("/debug/state")
    state = json.loads(body) if code == 200 else {}
    if code != 200 or "cache" not in state or "autopilot" not in state:
        n_errors += 1
        print(f"    FAILED: /debug/state ({code})")

    # Overhead: the ops plane's per-step cost is one tap per emitted event
    # (steady state: one step_time event per step). Composed against the
    # measured step time like bench.py's obs-overhead protocol — an A/B
    # wall-clock diff at <1% would drown in host noise.
    N = 20_000
    t0 = time.perf_counter()
    for _ in range(N):
        obs_events.emit_event("step_time", fn="overhead_probe", step=0, s=0.01)
    tap_ns = (time.perf_counter() - t0) / N * 1e9
    ops_pct = tap_ns / (step_s * 1e9) * 100.0
    monitor.shutdown_ops()
    if obs_events.ops_active():
        n_errors += 1
        print("    FAILED: taps still installed after shutdown_ops()")
    if ops_pct >= 1.0:
        n_errors += 1
        print(f"    FAILED: ops-plane overhead {ops_pct:.3f}% of the "
              f"{step_s * 1e3:.1f}ms step (budget < 1%)")
    else:
        print(f"    overhead OK: {tap_ns:.0f}ns/event = {ops_pct:.4f}% of "
              f"the {step_s * 1e3:.1f}ms step (< 1%); plane off installs "
              f"zero taps")

    print(f"\nlint_traces --ops: {n_errors} error(s)")
    return n_errors


def _roofline_smoke() -> int:
    """--roofline: continuous roofline ledger smoke (ISSUE 19;
    docs/performance.md "continuous roofline ledger"). On the CPU backend,
    asserts the tentpole acceptance behaviors end to end: a duty-cycled
    sampler on a gpt-tiny forward produces a schema-valid per-op ledger
    (>= 10 rows, every row in roofline.ROW_FIELDS) served live at
    /debug/roofline; a seeded mispriced op (its static roofline bound
    deflated 8x under the detectors' feet) trips a typed cost_model_drift
    anomaly through the DetectorBank; the armed-but-not-due per-step cost
    stays under 1% of the step; and with sampling off, zero probes run.
    Ends with the committed ROOFLINE_r*.json series gate. Returns the
    error count."""
    import json
    import time
    import urllib.error
    import urllib.request

    # Before any jit: annotated codegen is what stamps L<idx>.<sym> scopes
    # into HLO metadata so profiler rows attribute back to trace lines.
    os.environ.setdefault("THUNDER_TPU_ANNOTATE_TRACES", "1")

    import thunder_tpu as ttpu
    import thunder_tpu.monitor as monitor
    from thunder_tpu.models import gpt as m
    from thunder_tpu.observability import roofline as roofline_mod
    from thunder_tpu.observability.detect import DetectorConfig
    from thunder_tpu.observability.roofline import ROW_FIELDS, RooflineSampler

    n_errors = 0
    plane = monitor.serve(port=0,
                          detectors=DetectorConfig(min_samples=6, cooldown=20))
    print(f"--- roofline smoke: ops server on 127.0.0.1:{plane.port}")

    def get(route):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.port}{route}", timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg)
    idx = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 32)).astype(np.int32)
    jf = ttpu.jit(lambda p, i: m.forward(p, i, cfg), executors=["jax"])
    jf(params, idx)  # compile outside the sampled loop
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(jf(params, idx))
    step_s = (time.perf_counter() - t0) / 5

    # OFF (the default: THUNDER_TPU_ROOFLINE_EVERY unset -> every=0):
    # maybe_sample must never probe.
    off = RooflineSampler(jf)
    for _ in range(8):
        off.maybe_sample(jf, params, idx)
    if off.every != 0 or off.probes != 0 or len(off.ledger) != 0:
        n_errors += 1
        print(f"    FAILED: sampler off still probed (every={off.every}, "
              f"probes={off.probes})")
    else:
        print("    off OK: every=0 by default, 8 steps, zero probes")

    # ON: every=4 over 12 steps = exactly 3 probes; the ledger must come
    # back schema-valid with enough per-op rows to be a baseline.
    sampler = monitor.roofline(jf, every=4)
    for _ in range(12):
        sampler.maybe_sample(jf, params, idx)
    snap = sampler.ledger.snapshot()
    bad_rows = [r for r in snap["rows"] if set(r) != set(ROW_FIELDS)]
    priced = [r for r in snap["rows"] if r["roofline_us"] is not None]
    if (sampler.probes != 3 or snap["ops"] < 10 or bad_rows
            or len(priced) < 10):
        n_errors += 1
        print(f"    FAILED: ledger (probes={sampler.probes}, "
              f"ops={snap['ops']}, schema violations={len(bad_rows)}, "
              f"priced rows={len(priced)})")
    else:
        print(f"    ledger OK: 12 steps -> 3 probes, {snap['ops']} op rows, "
              f"schema-valid, {len(priced)} with roofline ceilings")

    code, body = get("/debug/roofline")
    live = json.loads(body) if code == 200 else {}
    if code != 200 or not live.get("enabled") \
            or live.get("ledger", {}).get("ops") != snap["ops"]:
        n_errors += 1
        print(f"    FAILED: /debug/roofline ({code}: {body[:120]})")
    else:
        print(f"    /debug/roofline OK: live ledger, "
              f"{live['ledger']['ops']} ops, {live['probes']} probes")

    # Seeded mispriced op: deflate the hottest op's static bound 8x in the
    # sampler's cost rows — the next probes' measured/predicted ratio walks
    # out of the band and the DetectorBank must raise cost_model_drift.
    top = sampler.ledger.rows()[0]
    seeded = 0
    for r in sampler._cost.rows:
        if r.sym == top.sym and r.index == top.line:
            r.roofline_s /= 8.0
            seeded += 1
    tripped = None
    for i in range(10):
        sampler.sample(jf, params, idx)
        kinds = [a.kind for a in plane.bank.recent_anomalies()]
        if "cost_model_drift" in kinds:
            tripped = i + 1
            break
    if not seeded or tripped is None:
        n_errors += 1
        print(f"    FAILED: seeded mispriced op ({top.label}, {seeded} cost "
              f"row(s) deflated) raised no cost_model_drift "
              f"(anomalies={sorted(set(kinds))})")
    else:
        a = next(a for a in plane.bank.recent_anomalies()
                 if a.kind == "cost_model_drift")
        print(f"    drift OK: {top.label} deflated 8x -> cost_model_drift "
              f"({a.severity}, ratio {a.value / a.baseline:.1f}x baseline) "
              f"after {tripped} probe(s)")

    # Overhead: the armed-but-not-due per-step cost is tick()'s counter
    # bump + modulo (maybe_sample then dispatches fn unchanged). Composed
    # against the measured step like bench.py's obs-overhead protocol.
    N = 50_000
    armed = RooflineSampler(jf, every=10**9)
    t0 = time.perf_counter()
    for _ in range(N):
        armed.tick()
    tick_ns = (time.perf_counter() - t0) / N * 1e9
    tick_pct = tick_ns / (step_s * 1e9) * 100.0
    if tick_pct >= 1.0:
        n_errors += 1
        print(f"    FAILED: armed duty-cycle overhead {tick_pct:.3f}% of "
              f"the {step_s * 1e3:.1f}ms step (budget < 1%)")
    else:
        print(f"    overhead OK: {tick_ns:.0f}ns/step armed = "
              f"{tick_pct:.4f}% of the {step_s * 1e3:.1f}ms step (< 1%)")

    monitor.shutdown_roofline()
    monitor.shutdown_ops()

    # The committed per-op series must gate (single round: absolute
    # invariants — >= 10 schema-valid rows with per-op gate keys).
    n_errors += _bench_history_gate("ROOFLINE_r*.json", min_rounds=1)

    print(f"\nlint_traces --roofline: {n_errors} error(s)")
    return n_errors


def _critpath_smoke() -> int:
    """--critpath: fleet critical-path ledger smoke (ISSUE 20;
    docs/observability.md "fleet timeline"). Drives a synthetic 4-host
    fleet through the armed TimelineRecorder and asserts the tentpole
    acceptance behaviors end to end: injected per-host clock skews are
    recovered from the lockstep-barrier rendezvous records within
    tolerance; per-step breakdowns assemble a schema-valid ledger served
    live at /debug/critpath (and a ``timeline`` component in /healthz); a
    seeded straggler host trips a ``bottleneck_shift`` anomaly through the
    DetectorBank naming that host; the static/predicted-vs-measured
    exposed-collective cross-check agrees within the noise floor; and the
    armed per-step cost stays under 1% of a measured gpt-tiny step. Ends
    with the committed CRITPATH_r*.json series gate. Returns the error
    count."""
    import json
    import time
    import urllib.error
    import urllib.request

    import thunder_tpu as ttpu
    import thunder_tpu.monitor as monitor
    from thunder_tpu.models import gpt as m
    from thunder_tpu.observability.detect import DetectorConfig
    from thunder_tpu.observability.timeline import CLASSES

    n_errors = 0
    plane = monitor.serve(
        port=0,
        detectors=DetectorConfig(
            min_samples=6, cooldown=20,
            critpath_min_steps=4, critpath_straggler_frac=0.25,
            critpath_cooldown=0,
        ),
    )
    print(f"--- critpath smoke: ops server on 127.0.0.1:{plane.port}")

    def get(route):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{plane.port}{route}", timeout=10) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    # A real measured step for the overhead budget denominator.
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg)
    idx = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 32)).astype(np.int32)
    jf = ttpu.jit(lambda p, i: m.forward(p, i, cfg), executors=["jax"])
    jf(params, idx)
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(jf(params, idx))
    step_s = (time.perf_counter() - t0) / 5

    # Armed recorder over a synthetic 4-host fleet: injected skews the
    # estimator must RECOVER (the falsifiable alignment loop), a static
    # wire split charging exposed-ICI/DCN, and the comm scheduler's
    # predicted exposed-pct for the three-way cross-check. event_sample=8
    # is the at-scale config: emitted events and gauge refreshes ride a
    # 1-in-8 duty cycle while the estimator/ledger/detector feed keep
    # full per-step fidelity (the assertions below all read in-process
    # state, so sampling cannot mask a recovery failure).
    injected = {"h0": 0.0, "h1": 0.12, "h2": -0.08, "h3": 0.04}
    rec = monitor.critpath(bank=plane.bank, emulated_skew_s=injected,
                           event_sample=8)
    rec.set_static_wire(0.10, 0.05, static_exposed_pct=15.0)
    rec.predicted_exposed_pct = 15.0

    BASE, DELAY, STALL = 0.050, 0.030, 0.004
    hosts = sorted(injected)
    for step in range(16):
        spans = {}
        for h in hosts:
            sp = dict(rec.static_spans(BASE))
            d = DELAY if (h == "h3" and 6 <= step < 14) else 0.0
            stall = STALL if step % 2 == 0 else 0.0
            sp["total_s"] = BASE + d + stall
            sp["stall_s"] = stall
            spans[h] = sp
            rec.note_collective(h, step, fn="fleet_step", s=0.0, step=step)
        rec.record_step(step, spans)

    # Skew recovery: estimates are relative to the fleet-median clock, so
    # compare against the injected offsets re-centered the same way.
    ests = rec.skew_estimates()
    med = sorted(injected.values())
    med = (med[1] + med[2]) / 2.0
    centered = {h: v - med for h, v in injected.items()}
    err_ms = max(abs(e.offset_s - centered[h]) * 1e3
                 for h, e in ests.items()) if ests else float("inf")
    outliers = [h for h, e in ests.items() if e.outlier]
    if len(ests) != 4 or err_ms > 5.0 or outliers:
        n_errors += 1
        print(f"    FAILED: skew recovery (hosts={len(ests)}, "
              f"err={err_ms:.3f}ms, outliers={outliers})")
    else:
        print(f"    skew OK: 4 hosts recovered within {err_ms:.3f}ms of "
              f"injected (120/-80/40ms spread), no false outliers")

    # Schema-valid ledger: every breakdown row carries the typed classes,
    # fractions sum to 1, and the straggler steps name the seeded host.
    snap = rec.ledger.snapshot(last=16)
    rows = snap["last_steps"]
    bad = [r for r in rows
           if set(r) != {"step", "total_s", "classes", "slowest_host",
                         "n_hosts"}
           or not set(r["classes"]) <= set(CLASSES)]
    fsum = sum(snap["fractions"].values())
    strag = snap["straggler_hosts"]
    if (snap["steps"] != 16 or bad or abs(fsum - 1.0) > 0.02
            or strag.get("h3", 0) < 6):
        n_errors += 1
        print(f"    FAILED: ledger (steps={snap['steps']}, "
              f"schema violations={len(bad)}, frac_sum={fsum:.3f}, "
              f"straggler_hosts={strag})")
    else:
        print(f"    ledger OK: 16 steps, schema-valid rows, fractions sum "
              f"{fsum:.3f}, straggler-wait on h3 x{strag['h3']}")

    # The seeded straggler must trip bottleneck_shift NAMING the host.
    shifts = [a for a in plane.bank.recent_anomalies()
              if a.kind == "bottleneck_shift"]
    named = [a for a in shifts if a.suspect_host == "h3"]
    if not named:
        n_errors += 1
        print(f"    FAILED: seeded straggler h3 raised no host-named "
              f"bottleneck_shift (got {[(a.kind, a.suspect_host) for a in shifts]})")
    else:
        a = named[0]
        print(f"    detector OK: bottleneck_shift ({a.severity}, "
              f"{a.detector}) names h3, straggler frac {a.value:.2f} vs "
              f"band {a.baseline:.2f}")

    # Static/predicted-vs-measured exposed-collective cross-check: the
    # synthetic spans are static-priced, so the deltas must sit inside the
    # perf gate's 10-point noise floor.
    cc = rec.crosscheck()
    d_static = cc.get("delta_static_pct")
    d_pred = cc.get("delta_predicted_pct")
    if (d_static is None or abs(d_static) > 10.0
            or d_pred is None or abs(d_pred) > 10.0):
        n_errors += 1
        print(f"    FAILED: exposed-pct cross-check ({cc})")
    else:
        print(f"    crosscheck OK: measured {cc['measured_exposed_pct']:.1f}% "
              f"vs static {cc['static_exposed_pct']:.1f}% "
              f"(d {d_static:+.2f}) / scheduler {cc['predicted_exposed_pct']:.1f}% "
              f"(d {d_pred:+.2f})")

    # Live surfaces: /debug/critpath serves the ledger + skew + crosscheck;
    # /healthz carries the timeline component (>= 2 hosts, aligned).
    code, body = get("/debug/critpath")
    live = json.loads(body) if code == 200 else {}
    if (code != 200 or not live.get("enabled")
            or live.get("ledger", {}).get("steps") != 16
            or "skew" not in live or "crosscheck" not in live):
        n_errors += 1
        print(f"    FAILED: /debug/critpath ({code}: {body[:120]})")
    else:
        print(f"    /debug/critpath OK: live ledger, "
              f"{live['ledger']['steps']} steps, "
              f"{len(live['skew'])} skew estimates")
    code, body = get("/healthz")
    verdict = json.loads(body) if body else {}
    tl_comp = (verdict.get("components") or {}).get("timeline")
    if tl_comp is None or tl_comp.get("hosts") != 4:
        n_errors += 1
        print(f"    FAILED: /healthz timeline component missing or wrong "
              f"({tl_comp})")
    else:
        print(f"    /healthz OK: timeline component "
              f"{tl_comp.get('status')}, {tl_comp['hosts']} hosts, "
              f"min confidence {tl_comp.get('min_confidence')}")

    # Overhead: the armed fleet-step cost (4 barrier records + one fold +
    # duty-cycled events/gauges) against the measured step, same protocol
    # as the roofline smoke. This one process plays ALL four hosts — a
    # real deployment spreads the barrier records across processes and
    # only the driver folds — so the budget holds the per-host share
    # under 1% while the full emulated composition is printed alongside.
    # Off-path (recorder not armed) is a None check in the driver —
    # literally zero.
    N = 2_000
    spans = {h: dict(rec.static_spans(BASE), total_s=BASE) for h in hosts}
    t0 = time.perf_counter()
    for i in range(N):
        for h in hosts:
            rec.note_collective(h, 1000 + i, fn="fleet_step", s=0.0,
                                step=1000 + i)
        rec.record_step(1000 + i, spans)
    per_step_ns = (time.perf_counter() - t0) / N * 1e9
    per_host_ns = per_step_ns / len(hosts)
    pct = per_host_ns / (step_s * 1e9) * 100.0
    if pct >= 1.0:
        n_errors += 1
        print(f"    FAILED: armed per-host cost {per_host_ns:.0f}ns = "
              f"{pct:.3f}% of the {step_s * 1e3:.1f}ms step (budget < 1%; "
              f"full {len(hosts)}-host emulation {per_step_ns:.0f}ns)")
    else:
        print(f"    overhead OK: {per_host_ns:.0f}ns/step/host armed = "
              f"{pct:.4f}% of the {step_s * 1e3:.1f}ms step (< 1%; full "
              f"{len(hosts)}-host emulation {per_step_ns:.0f}ns)")

    monitor.shutdown_critpath()
    monitor.shutdown_ops()

    # The committed fleet round must gate (single round: absolute
    # invariants — class coverage, skew recovery, attribution, citation).
    n_errors += _bench_history_gate("CRITPATH_r*.json", min_rounds=1)

    print(f"\nlint_traces --critpath: {n_errors} error(s)")
    return n_errors


def _chaos_multihost_smoke() -> int:
    """--chaos-multihost: re-exec this script on a virtual 8-device CPU mesh
    (the device-count flag must be set before jax initializes) and run
    :func:`_chaos_multihost_inner` there. Returns the error count."""
    import subprocess

    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "PYTHONPATH": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "THUNDER_TPU_RETRY_BACKOFF_S": "0",
    }
    cmd = [sys.executable, os.path.abspath(__file__), "--_chaos-multihost-inner"]
    print("--- chaos-multihost smoke (subprocess, 8 virtual devices)")
    r = subprocess.run(cmd, env=env, capture_output=True, text=True, timeout=1200)
    out = (r.stdout + r.stderr).strip().splitlines()
    for line in out[-40:]:
        print(f"    {line}")
    if r.returncode != 0:
        print(f"    FAILED: inner smoke exited {r.returncode}")
        return 1
    return 0


def _chaos_multihost_inner() -> int:
    """The mesh-wide chaos matrix (ISSUE 9 acceptance), run with 8 virtual
    devices: collective-hang → typed watchdog timeout naming trace line +
    suspected host; host-loss-at-step → checkpoint agreement → elastic
    resume on the shrunk mesh reproducing the uninterrupted loss
    trajectory; SDC injection → replica-checksum divergence → quarantine +
    re-run; all with paired fault_injected/recovery events validated by the
    replay correlation rule."""
    import json
    import tempfile

    import numpy as np

    import thunder_tpu.monitor as monitor
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events
    from thunder_tpu.core import dtypes
    from thunder_tpu.models import gpt as m
    from thunder_tpu.parallel import build_train_step, make_mesh
    from thunder_tpu.parallel.sharding import gpt_param_specs
    from thunder_tpu.parallel.train import opt_state_specs
    from thunder_tpu.resilience import chaos, elastic, watchdog
    from thunder_tpu.resilience.preemption import CheckpointManager, HostLost, run_training

    tmp = tempfile.mkdtemp(prefix="ttpu_mc_chaos_")
    log = os.path.join(tmp, "events.jsonl")
    monitor.set_event_log(log)
    n_errors = 0
    N_STEPS = 5
    LOSS_STEP = 2

    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    rng = np.random.RandomState(0)
    idx = rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    def build(mesh):
        specs = gpt_param_specs(cfg, mesh)
        step, opt0 = build_train_step(
            cfg, params, idx, tgt, mesh=mesh, param_specs=specs, lr=1e-2,
            executors=["jax"], donate=False,
        )

        def step_fn(state):
            p, o = state
            p, o, loss = step(p, o, idx, tgt)
            return (p, o), float(np.asarray(loss))

        return step_fn, opt0, specs

    mesh8 = make_mesh(fsdp=4, tp=2)
    step8, opt0, specs8 = build(mesh8)
    state0 = (params, opt0)

    print("--- chaos-multihost: un-faulted baseline trajectory")
    _, baseline = run_training(
        step8, state0, N_STEPS, manager=CheckpointManager(os.path.join(tmp, "base"))
    )
    print(f"    losses: {['%.4f' % x for x in baseline]}")

    print("--- chaos-multihost: collective hang -> typed watchdog timeout")
    # Join against PR 8's straggler data: host_health over synthetic per-host
    # step logs flags host 3; the timeout error must name it.
    hl = []
    for host in range(4):
        p = os.path.join(tmp, f"host{host}.jsonl")
        with open(p, "w") as f:
            for s in range(4):
                t = 0.4 if host == 3 else 0.1
                f.write(json.dumps({"v": 1, "ts": float(s), "seq": s, "pid": 1,
                                    "host": host, "kind": "step_time",
                                    "fn": "step", "step": s, "s": t}) + "\n")
        hl.append(p)
    summary, _ = monitor.host_health(hl)
    from thunder_tpu.distributed.runtime import compile_with_collectives
    from jax.sharding import PartitionSpec as P

    meshf = make_mesh(fsdp=8)
    w = rng.randn(16, 8).astype(np.float32) * 0.1
    x = rng.randn(4, 8).astype(np.float32)

    def loss_traced(w_shard, x):
        from thunder_tpu.distributed import prims as dist
        import thunder_tpu.clang as clang

        w_full = dist.synchronize(w_shard, "fsdp", 8, "fsdp")
        h = clang.matmul(x, clang.transpose(w_full, 0, 1))
        return clang.mean(clang.mul(h, h))

    jf, extrace = compile_with_collectives(
        loss_traced, (w[:2], x), meshf, (P("fsdp", None), P()),
        (P(), (P("fsdp", None), P())), grad=True,
    )
    watchdog.configure(0.25)
    try:
        with chaos.chaos_scope("collective_hang~5.0"):
            jf(w, x)
        n_errors += 1
        print("    FAILED: hang did not time out")
    except watchdog.CollectiveTimeoutError as e:
        ok_line = any("synchronize" in ln for ln in e.trace_lines)
        ok_host = e.suspected_host == summary["stragglers"][0]
        if ok_line and ok_host:
            print(f"    typed timeout OK: lines={e.trace_lines[:2]} "
                  f"suspect=host{e.suspected_host}")
        else:
            n_errors += 1
            print(f"    FAILED: lines={e.trace_lines} suspect={e.suspected_host}")
    finally:
        watchdog.configure(None)

    print("--- chaos-multihost: host loss -> checkpoint -> elastic resume (fsdp2-tp2)")
    mgr = CheckpointManager(os.path.join(tmp, "elastic"))
    try:
        with chaos.chaos_scope(f"host_loss@{LOSS_STEP}"):
            run_training(step8, state0, N_STEPS, manager=mgr, mesh=mesh8)
        n_errors += 1
        print("    FAILED: host loss did not fire")
    except HostLost as e:
        mesh4 = make_mesh(fsdp=2, tp=2)
        step4, _, specs4 = build(mesh4)
        st, start = elastic.elastic_resume(
            mgr, state0, mesh=mesh4, specs=(specs4, opt_state_specs(specs4))
        )
        if start != LOSS_STEP:
            n_errors += 1
            print(f"    FAILED: resumed at {start}, expected {LOSS_STEP}")
        cont = []
        state = st
        for _ in range(start, N_STEPS):
            state, loss = step4(state)
            cont.append(loss)
        if np.allclose(cont, baseline[LOSS_STEP:], rtol=1e-5):
            print(f"    elastic resume OK: {['%.4f' % x for x in cont]} matches "
                  f"the uninterrupted trajectory (reduction-order tolerance)")
        else:
            n_errors += 1
            print(f"    FAILED: resumed trajectory {cont} != baseline "
                  f"{baseline[LOSS_STEP:]}")

    print("--- chaos-multihost: SDC injection -> checksum guard -> re-run")
    try:
        with chaos.chaos_scope("sdc*1"):
            _, sdc_losses = run_training(
                step8, state0, N_STEPS,
                manager=CheckpointManager(os.path.join(tmp, "sdc")),
                sdc_guard=True,
            )
        if sdc_losses == baseline:
            print("    SDC quarantine + re-run OK: trajectory bitwise-equal")
        else:
            n_errors += 1
            print(f"    FAILED: SDC trajectory {sdc_losses} != {baseline}")
    except Exception as e:
        n_errors += 1
        print(f"    FAILED: {type(e).__name__}: {e}")

    print("--- chaos-multihost: event-log replay (correlation rule)")
    summary, diags = replay_events(log, storm_threshold=16)
    print(format_replay(summary, diags))
    n_errors += sum(1 for d in diags if d.severity >= Severity.ERROR)
    need = ("fault_injected", "collective_timeout", "host_loss",
            "checkpoint_save", "elastic_resume", "sdc_suspect", "sdc_rerun")
    missing = [k for k in need if not summary["kinds"].get(k)]
    if missing:
        n_errors += 1
        print(f"    FAILED: missing event kinds: {missing}")
    if summary.get("unrecovered_faults"):
        n_errors += 1
        print(f"    FAILED: unrecovered faults: {summary['unrecovered_faults']}")
    monitor.set_event_log(None)
    print(f"\nlint_traces --chaos-multihost: {n_errors} error(s)")
    return n_errors


_USAGE = ("usage: lint_traces.py [pattern] | --static | --schedule | --chaos | "
          "--chaos-multihost | --multichip | --soak | --federation | --hlo | "
          "--roofline | --critpath | --events <log.jsonl> [...] "
          "[--storm-threshold N]")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    if "--_chaos-multihost-inner" in argv:
        return 1 if _chaos_multihost_inner() else 0

    if "--_hlo-inner" in argv:
        return 1 if _hlo_inner() else 0

    if "--hlo" in argv:
        return 1 if _hlo_smoke() else 0

    if "--chaos-multihost" in argv:
        return 1 if _chaos_multihost_smoke() else 0

    if "--static" in argv:
        print("--- static smoke: liveness prediction vs instrument='memory'")
        return 1 if _static_smoke() else 0

    if "--schedule" in argv:
        return 1 if _schedule_smoke() else 0

    if "--soak" in argv:
        return 1 if _soak_smoke() else 0

    if "--federation" in argv:
        return 1 if _federation_smoke() else 0

    if "--ops" in argv:
        return 1 if _ops_smoke() else 0

    if "--roofline" in argv:
        return 1 if _roofline_smoke() else 0

    if "--critpath" in argv:
        return 1 if _critpath_smoke() else 0

    if "--chaos" in argv:
        return 1 if _chaos_smoke() else 0

    if "--multichip" in argv:
        return 1 if _multichip_smoke() else 0

    if "--events" in argv:
        i = argv.index("--events")
        paths = []
        for a in argv[i + 1:]:
            if a.startswith("--"):
                break
            paths.append(a)
        storm = 4
        if "--storm-threshold" in argv:
            j = argv.index("--storm-threshold")
            try:
                storm = int(argv[j + 1])
            except (IndexError, ValueError):
                print(_USAGE, file=sys.stderr)
                return 2
        if not paths:
            print(_USAGE, file=sys.stderr)
            return 2
        try:
            return _replay(paths, storm)
        except OSError as e:
            print(f"lint_traces --events: cannot read {paths}: {e}", file=sys.stderr)
            return 2

    pattern = argv[0] if argv else ""

    from thunder_tpu.analysis import Severity, TraceVerificationError
    from thunder_tpu.examine import lint

    n_errors = n_warnings = 0

    for name, fn, args in _programs():
        if pattern not in name:
            continue
        print(f"--- lint: {name}")
        # Kernel executors are environment-sensitive; the jax executor claims
        # every prim, which is what the pipeline verification needs.
        diags = lint(fn, *args, executors=["jax"], verbose=False)
        errs = [d for d in diags if d.severity >= Severity.ERROR]
        warns = [d for d in diags if d.severity == Severity.WARNING]
        n_errors += len(errs)
        n_warnings += len(warns)
        for d in errs + warns:
            print(d.format())
        print(f"    {len(errs)} error(s), {len(warns)} warning(s)")

    for name, staged, args in _grad_workloads():
        if pattern not in name:
            continue
        print(f"--- verify (compiled, debug_checks=True): {name}")
        try:
            staged(*args)
            print("    all passes verified clean")
        except TraceVerificationError as e:
            n_errors += 1
            print(f"    FAILED: {e}")

    # CI half of the perf observatory (ISSUE 5/8): a committed bench round
    # regressing beyond threshold — single-host or multichip series — fails
    # the lint run, not just a human's eye.
    if not pattern:
        n_errors += _bench_history_gate()
        n_errors += _bench_history_gate("MULTICHIP_BENCH_r*.json")
        n_errors += _bench_history_gate("SOAK_r*.json")
        n_errors += _bench_history_gate("SOAK_POD_r*.json", min_rounds=1)
        n_errors += _bench_history_gate("ROOFLINE_r*.json", min_rounds=1)
        n_errors += _bench_history_gate("CRITPATH_r*.json", min_rounds=1)

    print(f"\nlint_traces: {n_errors} error(s), {n_warnings} warning(s)")
    return 1 if n_errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
