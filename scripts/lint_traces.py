#!/usr/bin/env python
"""Run the static trace verifier over the repo's example programs.

CI/tooling entry point for the analysis/ framework (see
docs/trace_invariants.md): every program below is traced, pushed through the
default pass pipeline (acquisition → DCE → CSE → claiming → del_last_used)
with `examine.lint`, and — for the gradient workloads — compiled end-to-end
under THUNDER_TPU_CHECKS=1 so each transform pass (autodiff joint rewrite,
autocast, RNG functionalization) is verified at the point it runs.

Exit status is non-zero if any ERROR-severity diagnostic is found.

Usage:
    python scripts/lint_traces.py            # all programs
    python scripts/lint_traces.py gpt        # substring-filter by name
    python scripts/lint_traces.py --events LOG.jsonl
        # replay an observability event log (THUNDER_TPU_EVENTS /
        # jit(events=...)): validates the JSONL schema and flags recompile
        # storms (thunder_tpu.analysis.events; docs/observability.md)
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _programs():
    """(name, fn, args) — the example-program corpus: the ops exercised by
    examples/train.py's training step plus representative small programs."""
    import thunder_tpu.torch as ttorch
    from thunder_tpu.models import gpt as m
    from thunder_tpu.core import dtypes

    rng = np.random.RandomState(0)
    x44 = rng.randn(4, 4).astype(np.float32)
    x48 = rng.randn(4, 8).astype(np.float32)
    w86 = rng.randn(6, 8).astype(np.float32)

    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)

    return [
        ("elementwise-chain", lambda a: ((a * 2.0).tanh() + a).sum(), (x44,)),
        ("linear-gelu", lambda a, w: ttorch.sum(ttorch.gelu(ttorch.linear(a, w))), (x48, w86)),
        ("reduction-mix", lambda a: (a.sum(0) * a.mean()).sum(), (x44,)),
        ("dropout-rng", lambda a: ttorch.dropout(a, p=0.5, training=True).sum(), (x44,)),
        ("inplace-functionalized", _inplace_prog, (x44,)),
        ("gpt-tiny-forward", lambda p, i: m.forward(p, i, cfg), (params, idx)),
        ("gpt-tiny-loss", lambda p, i, t: m.loss_fn(p, i, t, cfg), (params, idx, tgt)),
    ]


def _inplace_prog(a):
    import thunder_tpu.torch as ttorch

    b = ttorch.abs(a)
    b += 1.0
    return ttorch.sum(b)


def _grad_workloads():
    """(name, staged callable, args) compiled with the verifier scoped on —
    exercises the grad/autocast/RNG transform passes the pipeline-level lint
    stages don't reach."""
    import thunder_tpu as ttpu
    from thunder_tpu.models import gpt as m
    from thunder_tpu.core import dtypes

    rng = np.random.RandomState(0)
    cfg = m.name_to_config("gpt-tiny")
    params = m.init_params(cfg, dtype=dtypes.float32, seed=0)
    idx = rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    loss = lambda p, i, t: m.loss_fn(p, i, t, cfg)  # noqa: E731

    return [
        ("gpt-tiny-backward", ttpu.value_and_grad(loss, executors=["jax"], debug_checks=True),
         (params, idx, tgt)),
        ("gpt-tiny-backward-autocast",
         ttpu.value_and_grad(loss, executors=["jax"], debug_checks=True, autocast="bfloat16"),
         (params, idx, tgt)),
    ]


def _replay(path: str, storm_threshold: int) -> int:
    from thunder_tpu.analysis import Severity
    from thunder_tpu.analysis.events import format_replay, replay_events

    summary, diags = replay_events(path, storm_threshold=storm_threshold)
    print(format_replay(summary, diags))
    n_errors = sum(1 for d in diags if d.severity >= Severity.ERROR)
    print(f"\nlint_traces --events: {n_errors} error(s), "
          f"{sum(1 for d in diags if d.severity == Severity.WARNING)} warning(s)")
    return 1 if n_errors else 0


_USAGE = "usage: lint_traces.py [pattern] | --events <log.jsonl> [--storm-threshold N]"


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)

    if "--events" in argv:
        i = argv.index("--events")
        path = argv[i + 1] if i + 1 < len(argv) and not argv[i + 1].startswith("--") else None
        storm = 4
        if "--storm-threshold" in argv:
            j = argv.index("--storm-threshold")
            try:
                storm = int(argv[j + 1])
            except (IndexError, ValueError):
                print(_USAGE, file=sys.stderr)
                return 2
        if path is None:
            print(_USAGE, file=sys.stderr)
            return 2
        try:
            return _replay(path, storm)
        except OSError as e:
            print(f"lint_traces --events: cannot read {path!r}: {e}", file=sys.stderr)
            return 2

    pattern = argv[0] if argv else ""

    from thunder_tpu.analysis import Severity, TraceVerificationError
    from thunder_tpu.examine import lint

    n_errors = n_warnings = 0

    for name, fn, args in _programs():
        if pattern not in name:
            continue
        print(f"--- lint: {name}")
        # Kernel executors are environment-sensitive; the jax executor claims
        # every prim, which is what the pipeline verification needs.
        diags = lint(fn, *args, executors=["jax"], verbose=False)
        errs = [d for d in diags if d.severity >= Severity.ERROR]
        warns = [d for d in diags if d.severity == Severity.WARNING]
        n_errors += len(errs)
        n_warnings += len(warns)
        for d in errs + warns:
            print(d.format())
        print(f"    {len(errs)} error(s), {len(warns)} warning(s)")

    for name, staged, args in _grad_workloads():
        if pattern not in name:
            continue
        print(f"--- verify (compiled, debug_checks=True): {name}")
        try:
            staged(*args)
            print("    all passes verified clean")
        except TraceVerificationError as e:
            n_errors += 1
            print(f"    FAILED: {e}")

    print(f"\nlint_traces: {n_errors} error(s), {n_warnings} warning(s)")
    return 1 if n_errors else 0


if __name__ == "__main__":
    raise SystemExit(main())
