"""Long-horizon quantized-training evidence (VERDICT r4 #6).

Trains pythia-160m for N iterations three ways — bf16 baseline, int8
everywhere, and int8 with the lm_head excluded (the TE skip_modules recipe,
reference: transformer_engineex.py:398-437) — on the SAME synthetic data
stream, and writes the loss curves + timing to a JSON file for PARITY.md.

Usage: python scripts/quant_convergence.py [iters] [out.json]
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

MODEL = "pythia-160m"
B, T = 4, 1024
ITERS = int(sys.argv[1]) if len(sys.argv) > 1 else 200
OUT = sys.argv[2] if len(sys.argv) > 2 else "/tmp/quant_convergence.json"
LR, WD = 3e-4, 0.1


def run(tag: str, executors, skip_out=()):
    from thunder_tpu.core import dtypes
    from thunder_tpu.executors.quantex import QuantRecipe, set_recipe
    from thunder_tpu.models import gpt
    from thunder_tpu.parallel import build_train_step

    set_recipe(QuantRecipe(skip_out_features=tuple(skip_out)))
    cfg = gpt.name_to_config(MODEL)
    params = gpt.init_params(cfg, dtype=dtypes.bfloat16, device_init=True, seed=0)
    rng = np.random.RandomState(0)  # identical stream for every variant

    # A small FIXED dataset cycled every 8 steps: the model genuinely learns
    # (memorizes) it, so the loss curves separate if the quantized numerics
    # hurt optimization — a fresh-random stream only ever approaches the
    # uniform entropy and would hide a real gap.
    batches = [
        rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32) for _ in range(8)
    ]

    idx = batches[0]
    tgt = np.roll(idx, -1, axis=1).astype(np.int32)
    step, opt = build_train_step(
        cfg, params, idx, tgt, lr=LR, weight_decay=WD, optimizer="adamw",
        executors=executors,
    )
    params, opt, loss = step(params, opt, idx, tgt)
    losses = [float(np.asarray(loss))]

    t0 = time.perf_counter()
    prev = None
    for i in range(ITERS - 1):
        idx = batches[(i + 1) % len(batches)]
        tgt = np.roll(idx, -1, axis=1).astype(np.int32)
        params, opt, loss = step(params, opt, idx, tgt)
        if prev is not None:
            losses.append(float(np.asarray(prev)))
        prev = loss
    losses.append(float(np.asarray(prev)))
    dt = time.perf_counter() - t0
    set_recipe(QuantRecipe())  # restore default
    print(f"# {tag}: {ITERS} iters {dt:.1f}s avg {dt / max(ITERS - 1, 1):.4f}s/iter "
          f"loss {losses[0]:.3f}->{losses[-1]:.3f}", file=sys.stderr)
    return {"losses": losses, "iters": ITERS, "avg_iter_s": round(dt / max(ITERS - 1, 1), 4)}


def main():
    from thunder_tpu.api import _ensure_runtime
    from thunder_tpu.models import gpt

    _ensure_runtime()
    vocab_padded = gpt.name_to_config(MODEL).padded_vocab_size
    results = {
        "model": MODEL, "batch": B, "seq": T,
        "bf16": run("bf16", None),
        "int8_all": run("int8_all", ["quant", "pallas", "flash", "jax"]),
        "int8_skip_lm_head": run(
            "int8_skip_lm_head", ["quant", "pallas", "flash", "jax"],
            skip_out=(vocab_padded,),
        ),
    }
    # Convergence-gap summary at a few horizons.
    for k in ("int8_all", "int8_skip_lm_head"):
        gaps = {}
        for h in (10, 50, 100, ITERS):
            if h <= ITERS:
                gaps[str(h)] = round(
                    results[k]["losses"][h - 1] - results["bf16"]["losses"][h - 1], 4
                )
        results[k]["loss_gap_vs_bf16"] = gaps
    with open(OUT, "w") as f:
        json.dump(results, f)
    print(json.dumps({k: v for k, v in results.items() if not isinstance(v, dict)} |
                     {k: {"final_loss": v["losses"][-1], "avg_iter_s": v["avg_iter_s"],
                          "gap": v.get("loss_gap_vs_bf16")}
                      for k, v in results.items() if isinstance(v, dict)}))


if __name__ == "__main__":
    main()
