"""Profile the bench training step on the real TPU and dump per-op times.

Usage: python scripts/profile_train.py [outdir]
Writes an xplane profile then parses it with xprof into a per-HLO-op table.
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/prof_train"
    import jax
    import numpy as np

    from bench import build_train, TRAIN_B, TRAIN_T
    from thunder_tpu.api import _ensure_runtime

    _ensure_runtime()
    jfn, flat_params, idx, tgt, init_s, trace_s, stage_s = build_train("open_llama_3b", TRAIN_B, TRAIN_T)

    t0 = time.perf_counter()
    flat_params, loss = jfn(flat_params, idx, tgt)
    loss.block_until_ready()
    print(f"compile+first: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    # warm
    for _ in range(2):
        flat_params, loss = jfn(flat_params, idx, tgt)
    loss.block_until_ready()

    with jax.profiler.trace(outdir):
        for _ in range(3):
            flat_params, loss = jfn(flat_params, idx, tgt)
        loss.block_until_ready()
    print(f"profile written to {outdir}", file=sys.stderr)


if __name__ == "__main__":
    main()
