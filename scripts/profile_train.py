"""Profile the bench training step on the real TPU and dump per-op times.

Usage: python scripts/profile_train.py [outdir]

Thin driver over ``thunder_tpu.profile`` (observability/profile.py): brackets
3 warm steps with jax.profiler StepTraceAnnotations and writes an xplane
profile; parse per-HLO-op self-times with xprof (``hlo_stats``). Run with
``THUNDER_TPU_ANNOTATE_TRACES=1`` to stamp trace-line + pass provenance into
HLO metadata so profiler rows map back to BoundSymbols
(docs/observability.md).
"""
from __future__ import annotations

import sys
import time

sys.path.insert(0, ".")


def main():
    outdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/prof_train"

    from bench import build_train, TRAIN_B, TRAIN_T
    from thunder_tpu.api import _ensure_runtime
    from thunder_tpu.observability.profile import profile

    _ensure_runtime()
    jfn, flat_params, idx, tgt, init_s, trace_s, stage_s, *_static = build_train(
        "open_llama_3b", TRAIN_B, TRAIN_T
    )

    t0 = time.perf_counter()
    flat_params, loss = jfn(flat_params, idx, tgt)
    loss.block_until_ready()
    print(f"compile+first: {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    # Params are donated: thread them through a closure so every profiled
    # step consumes the previous step's buffers, exactly like the train loop.
    state = {"p": flat_params}

    def step():
        state["p"], loss = jfn(state["p"], idx, tgt)
        return loss

    res = profile(step, trace_dir=outdir, steps=3, warmup=2)
    print(
        f"profile written to {res['trace_dir']} "
        f"(avg step {res['avg_s']:.4f}s, profiler={'ok' if res['profiler'] else 'WALL-CLOCK ONLY'})",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
